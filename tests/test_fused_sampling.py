"""ops.fused_sampling — the one-pass fused decode-step epilogue.

Contracts under test (ISSUE 14):

- the XLA reference (the engines' ``sample_dynamic`` target) is
  BITWISE the historical sort-based composition — the ``lax.cond``
  sort short-circuit added for all-greedy / plain-temperature steps
  must be invisible in the tokens on either side of its predicate;
- the Pallas kernel (interpret mode — hermetic on CPU) is
  token-identical to the reference across the whole parameter grid:
  greedy / temperature-only / top-k / top-p / combined / disabled
  filters, bf16 logits, every vocab tile, ragged row counts (the
  row-block padding path), and the spec-step width axis (``1 + K``
  positions per row, per-position keys);
- the in-kernel Gumbel field replays jax's threefry-2x32 PRNG
  bit-for-bit (the key-for-key chain-identity guarantee rests on it —
  a jax PRNG change must fail HERE, loudly, not as a silent sampling
  drift in serving);
- the serving engines ride the fused epilogue at the unchanged 5×1
  executable budget with zero steady-state retraces, sampled chains
  stay identical between the spec (width-axis) and plain decode
  paths under eos/budget truncation, and the vocab-tile autotune
  winner is adopted through ``fused_sample(block_v=0)``.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu.models import GPTConfig, GPTModel
from apex_tpu.ops import autotune
from apex_tpu.ops.fused_sampling import (
    fused_sample,
    fused_sample_reference,
    sampling_cost_bytes,
)
from apex_tpu.serving import PagedEngine
from apex_tpu.serving.engine import sample_dynamic
from apex_tpu.utils import tracecheck

V = 512                       # % 128 == 0: inside the kernel envelope
R = 13                        # not a row-block multiple: padding path


def _legacy_sample_dynamic(logits, keys, temperature, top_k, top_p,
                           vocab_size):
    """The pre-fusion ``sample_dynamic`` body, verbatim — the golden
    pin the refactored reference must reproduce bit-for-bit."""
    logits = logits.astype(jnp.float32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    safe_t = jnp.maximum(temperature, 1e-6)[:, None]
    scaled = logits / safe_t
    k = jnp.where(top_k > 0, top_k, vocab_size)
    ordered = jnp.sort(scaled, axis=-1)
    kth = jnp.take_along_axis(
        ordered, (vocab_size - k)[:, None], axis=-1)
    scaled = jnp.where(scaled < kth, -1e30, scaled)
    p_on = (top_p > 0.0) & (top_p < 1.0)
    rev = ordered[:, ::-1]
    desc = jnp.where(rev < kth, -1e30, rev)
    probs = jax.nn.softmax(desc, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = cum - probs < jnp.where(p_on, top_p, 1.0)[:, None]
    thresh = jnp.min(jnp.where(keep, desc, jnp.inf), axis=-1,
                     keepdims=True)
    scaled = jnp.where(p_on[:, None] & (scaled < thresh), -1e30,
                       scaled)
    sampled = jax.vmap(jax.random.categorical)(keys, scaled)
    return jnp.where(temperature > 0.0, sampled.astype(jnp.int32),
                     greedy)


def _grid_case(rng, r=R, v=V):
    logits = jnp.asarray(rng.normal(size=(r, v)) * 3, jnp.float32)
    keys = jax.vmap(jax.random.PRNGKey)(
        jnp.asarray(rng.integers(0, 2**31, r), jnp.uint32))
    temp = jnp.asarray(rng.choice([0.0, 0.3, 0.7, 1.0, 1.5], r),
                       jnp.float32)
    tk = jnp.asarray(rng.choice([0, 1, 5, 40, v], r), jnp.int32)
    tp = jnp.asarray(rng.choice([0.0, 0.1, 0.5, 0.9, 0.99, 1.0], r),
                     jnp.float32)
    return logits, keys, temp, tk, tp


class TestReferenceIsLegacySampler:
    """The cond-gated reference == the historical sort-based math."""

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_mixed_grid_bitwise(self, seed):
        rng = np.random.default_rng(seed)
        logits, keys, temp, tk, tp = _grid_case(rng)
        ref = _legacy_sample_dynamic(logits, keys, temp, tk, tp, V)
        got = fused_sample_reference(logits, keys, temp, tk, tp, V)
        assert jnp.array_equal(ref, got)
        # serving's sample_dynamic delegates here
        assert jnp.array_equal(
            ref, sample_dynamic(logits, keys, temp, tk, tp, V))

    def test_short_circuit_side_is_exact(self):
        """All filters disabled — the cond takes the sort-free branch
        (top_k == 0 everywhere, top_p disabled both ways) and must
        still be bitwise the full legacy path."""
        rng = np.random.default_rng(7)
        logits = jnp.asarray(rng.normal(size=(R, V)), jnp.float32)
        keys = jax.vmap(jax.random.PRNGKey)(
            jnp.asarray(rng.integers(0, 2**31, R), jnp.uint32))
        temp = jnp.asarray(rng.choice([0.0, 0.7, 1.3], R), jnp.float32)
        zeros = jnp.zeros((R,), jnp.int32)
        for tp_off in (jnp.zeros((R,), jnp.float32),
                       jnp.ones((R,), jnp.float32)):
            ref = _legacy_sample_dynamic(logits, keys, temp, zeros,
                                         tp_off, V)
            got = fused_sample_reference(logits, keys, temp, zeros,
                                         tp_off, V)
            assert jnp.array_equal(ref, got)

    def test_top_k_equal_vocab_is_filter_branch_noop(self):
        """top_k == vocab crosses the predicate (filters branch) but
        masks nothing — exactness of the disabled-filter contract on
        the OTHER side of the cond."""
        rng = np.random.default_rng(9)
        logits = jnp.asarray(rng.normal(size=(R, V)), jnp.float32)
        keys = jax.vmap(jax.random.PRNGKey)(
            jnp.asarray(rng.integers(0, 2**31, R), jnp.uint32))
        temp = jnp.full((R,), 0.9, jnp.float32)
        full_k = jnp.full((R,), V, jnp.int32)
        tp = jnp.zeros((R,), jnp.float32)
        ref = _legacy_sample_dynamic(logits, keys, temp,
                                     jnp.zeros((R,), jnp.int32), tp, V)
        got = fused_sample_reference(logits, keys, temp, full_k, tp, V)
        assert jnp.array_equal(ref, got)


class TestKernelGoldenParity:
    """Interpret-mode kernel vs reference, token for token."""

    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    @pytest.mark.parametrize("block_v", [V, 128])
    def test_mixed_grid(self, seed, block_v):
        rng = np.random.default_rng(seed)
        logits, keys, temp, tk, tp = _grid_case(rng)
        ref = fused_sample_reference(logits, keys, temp, tk, tp, V)
        got = fused_sample(logits, keys, temp, tk, tp,
                           implementation="pallas_interpret",
                           block_v=block_v)
        assert jnp.array_equal(ref, got)

    def test_bf16_logits(self):
        rng = np.random.default_rng(5)
        logits, keys, temp, tk, tp = _grid_case(rng)
        lb = logits.astype(jnp.bfloat16)
        ref = fused_sample_reference(lb, keys, temp, tk, tp, V)
        got = fused_sample(lb, keys, temp, tk, tp,
                           implementation="pallas_interpret",
                           block_v=256)
        assert jnp.array_equal(ref, got)

    def test_single_row_and_tiny_batch(self):
        rng = np.random.default_rng(6)
        for r in (1, 2):
            logits, keys, temp, tk, tp = _grid_case(rng, r=r)
            ref = fused_sample_reference(logits, keys, temp, tk, tp, V)
            got = fused_sample(logits, keys, temp, tk, tp,
                               implementation="pallas_interpret")
            assert jnp.array_equal(ref, got)

    @pytest.mark.parametrize("w", [2, 4])
    def test_width_axis_matches_per_position_loop(self, w):
        """The spec-step form: (rows, w, vocab) + per-position keys in
        ONE call == w separate sample_dynamic passes."""
        rng = np.random.default_rng(8)
        logits = jnp.asarray(rng.normal(size=(R, w, V)) * 3,
                             jnp.float32)
        keys = jnp.stack(
            [jax.vmap(jax.random.PRNGKey)(
                jnp.asarray(rng.integers(0, 2**31, R), jnp.uint32))
             for _ in range(w)], axis=1)
        _, _, temp, tk, tp = _grid_case(rng)
        ref = jnp.stack(
            [_legacy_sample_dynamic(logits[:, j], keys[:, j], temp,
                                    tk, tp, V) for j in range(w)],
            axis=1)
        for impl in ("xla", "pallas_interpret"):
            got = fused_sample(logits, keys, temp, tk, tp,
                               implementation=impl, block_v=128)
            assert jnp.array_equal(ref, got), impl

    def test_greedy_rows_are_pure_argmax(self):
        """temperature <= 0 == fp32 argmax — the generate() parity
        anchor (same argmax the static sample_logits path takes)."""
        rng = np.random.default_rng(4)
        logits = jnp.asarray(rng.normal(size=(R, V)), jnp.float32)
        keys = jax.vmap(jax.random.PRNGKey)(jnp.zeros(R, jnp.uint32))
        zt = jnp.zeros((R,), jnp.float32)
        zk = jnp.zeros((R,), jnp.int32)
        got = fused_sample(logits, keys, zt, zk, zt,
                           implementation="pallas_interpret")
        assert jnp.array_equal(got, jnp.argmax(logits, axis=-1))

    def test_validation(self):
        rng = np.random.default_rng(0)
        logits, keys, temp, tk, tp = _grid_case(rng)
        with pytest.raises(ValueError, match="keys shape"):
            fused_sample(logits, keys[:-1], temp, tk, tp)
        with pytest.raises(ValueError, match="vocab_size"):
            fused_sample(logits, keys, temp, tk, tp, vocab_size=V + 1)
        with pytest.raises(ValueError, match="temperature shape"):
            fused_sample(logits, keys, temp[:-1], tk, tp)
        with pytest.raises(ValueError, match="logits must be"):
            fused_sample(logits[0], keys, temp, tk, tp)

    def test_unaligned_vocab_falls_back_to_reference(self):
        """V % 128 != 0 is outside the kernel envelope: auto must
        resolve to the reference, not crash."""
        rng = np.random.default_rng(2)
        logits, keys, temp, tk, tp = _grid_case(rng, v=300)
        ref = fused_sample_reference(logits, keys, temp, tk, tp, 300)
        got = fused_sample(logits, keys, temp, tk, tp)
        assert jnp.array_equal(ref, got)


class TestThreefryReplay:
    """The kernel's Gumbel field == jax.random's, bit for bit.  If a
    jax upgrade changes the default PRNG layout this fails loudly —
    the serving chain-identity contract depends on it."""

    def test_gumbel_bits_match(self):
        from apex_tpu.ops.fused_sampling import (
            _threefry2x32, _TINY)
        keys = jax.vmap(jax.random.PRNGKey)(
            jnp.arange(5, dtype=jnp.uint32) * 13 + 1)
        half = V // 2
        c0 = jnp.arange(half, dtype=jnp.uint32)[None, :]
        r0, r1 = _threefry2x32(keys[:, 0:1], keys[:, 1:2], c0,
                               c0 + jnp.uint32(half))
        bits = jnp.concatenate([r0, r1], axis=1)
        fb = (bits >> jnp.uint32(9)) | jnp.uint32(0x3F800000)
        floats = jax.lax.bitcast_convert_type(fb, jnp.float32) - 1.0
        u = jnp.maximum(_TINY,
                        floats * (jnp.float32(1.0) - _TINY) + _TINY)
        mine = -jnp.log(-jnp.log(u))
        ref = jax.vmap(
            lambda k: jax.random.gumbel(k, (V,), jnp.float32))(keys)
        assert jnp.array_equal(mine, ref), (
            "jax's threefry/gumbel layout changed — the fused sampling "
            "kernel's key-for-key chain identity no longer holds; "
            "update _sampling_kernel's pass 5 to the new layout")

    def test_categorical_decision_matches(self):
        rng = np.random.default_rng(1)
        logits = jnp.asarray(rng.normal(size=(4, V)), jnp.float32)
        keys = jax.vmap(jax.random.PRNGKey)(
            jnp.asarray(rng.integers(0, 2**31, 4), jnp.uint32))
        temp = jnp.ones((4,), jnp.float32)
        zk = jnp.zeros((4,), jnp.int32)
        zp = jnp.zeros((4,), jnp.float32)
        got = fused_sample(logits, keys, temp, zk, zp,
                           implementation="pallas_interpret")
        ref = jax.vmap(jax.random.categorical)(keys, logits)
        assert jnp.array_equal(got, ref.astype(jnp.int32))


class TestAutotuneAdoption:
    def test_cached_tile_adopted_by_block_v_zero(self, monkeypatch):
        """fused_sample(block_v=0) queries the (vocab, width) winner —
        the engine-side adoption path (the engines always pass 0)."""
        calls = []
        real = autotune.cached_sampling_tile

        def spy(vocab, width):
            calls.append((vocab, width))
            return 128

        monkeypatch.setattr(autotune, "cached_sampling_tile", spy)
        rng = np.random.default_rng(3)
        logits, keys, temp, tk, tp = _grid_case(rng)
        got = fused_sample(logits, keys, temp, tk, tp,
                           implementation="pallas_interpret",
                           block_v=0)
        assert calls == [(V, 1)]
        monkeypatch.setattr(autotune, "cached_sampling_tile", real)
        ref = fused_sample(logits, keys, temp, tk, tp,
                           implementation="pallas_interpret",
                           block_v=128)
        assert jnp.array_equal(ref, got)

    def test_tune_fused_sampling_writes_width_qualified_keys(
            self, tmp_path, monkeypatch):
        monkeypatch.setenv("APEX_TPU_AUTOTUNE_CACHE",
                           str(tmp_path / "at.json"))
        autotune.clear_cache()
        try:
            best = autotune.tune_fused_sampling(
                n_rows=4, width=256, sample_width=1,
                candidates=(128, 256),
                implementation="pallas_interpret")
            assert best in (128, 256)
            assert autotune.cached_sampling_tile(256, 1) == best
            # width-qualified: the spec step's entry is separate
            assert autotune.cached_sampling_tile(256, 3) is None
            assert autotune.cached_sampling_tile(512, 1) is None
        finally:
            autotune.clear_cache()

    def test_cost_model_is_one_pass(self):
        """The declared kernel traffic ~ one logits read: the analytic
        number the decode_epilogue bench leg reports."""
        got = sampling_cost_bytes(8, V, jnp.float32)
        assert 8 * V * 4 <= got <= 8 * V * 4 + 8 * 64
        assert sampling_cost_bytes(8, V, jnp.bfloat16) < got


def _tiny_gpt():
    cfg = GPTConfig.tiny(position_embedding="learned",
                         scan_layers=True)
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))
    return model, {"params": params["params"]}


@pytest.fixture(scope="module")
def gpt():
    return _tiny_gpt()


class TestEngineFusedEpilogue:
    """Engine-level acceptance: the fused epilogue rides the serving
    engines at the unchanged 5×1 executable budget, and the spec
    step's width-axis sampling keeps chains identical to plain decode
    under eos/budget truncation."""

    def test_spec_chain_identical_to_plain_decode_with_eos(self, gpt):
        """The width-axis call's eos/budget interaction: a drafted
        engine (forced drafts) and an undrafted engine must emit
        IDENTICAL sampled chains for the same seeds — eos and budget
        truncation included (acceptance-invariance rides the same
        sequential key chain the fused width call consumes)."""
        model, params = gpt
        prompt = np.asarray([5, 9, 2, 9, 2, 9], np.int32)

        def run(spec):
            eng = PagedEngine(model, params, max_slots=2,
                              block_size=8, prefill_chunk=4,
                              spec_tokens=(3 if spec else 0))
            if spec:
                eng._drafter = lambda context, k, ngram: np.zeros(
                    (k,), np.int32)
            eng.admit(0, prompt, max_new_tokens=8, temperature=0.9,
                      top_k=7, top_p=0.9, eos_id=3, seed=123)
            out = []
            for _ in range(40):
                step = eng.step()
                n = int(step.counts[0])
                out.extend(int(t) for t in step.tokens[0, :n])
                if step.finished[0]:
                    break
                if eng._tenants[0] is None:
                    break
            eng.release(0)
            return out

        assert run(spec=True) == run(spec=False)

    def test_zero_retrace_soak_at_5x1_budget(self, gpt):
        """The trace-budget acceptance: mixed greedy/temp/top-k/top-p
        traffic + drafting through the fused epilogue — FIVE
        executables × 1 trace, zero steady-state retraces."""
        model, params = gpt
        eng = PagedEngine(model, params, max_slots=3, block_size=8,
                          prefill_chunk=4, spec_tokens=2)
        eng.warmup()
        budget = {"decode_step": 1, "prefill_step": 1, "spec_step": 1,
                  "admit": 1, "release": 1}
        assert eng.trace_counts == budget
        before = tracecheck.trace_event_count()
        rng = np.random.default_rng(0)
        cases = [dict(temperature=0.0),
                 dict(temperature=0.8),
                 dict(temperature=0.9, top_k=5),
                 dict(temperature=1.1, top_p=0.9),
                 dict(temperature=0.7, top_k=9, top_p=0.8)]
        slot_live = {}
        seq = 0
        for it in range(25):
            for slot in range(3):
                if slot_live.get(slot) is None and seq < len(cases) * 2:
                    kw = cases[seq % len(cases)]
                    plen = int(rng.integers(2, 9))
                    eng.admit(slot,
                              rng.integers(1, 40, plen).astype(np.int32),
                              max_new_tokens=int(rng.integers(2, 6)),
                              seed=seq, **kw)
                    slot_live[slot] = True
                    seq += 1
            if not any(slot_live.values()):
                break
            out = eng.step()
            for slot in range(3):
                if slot_live.get(slot) and (
                        bool(out.finished[slot])
                        or eng._tenants[slot] is None):
                    if eng._tenants[slot] is not None:
                        eng.release(slot)
                    slot_live[slot] = False
        assert tracecheck.trace_event_count() == before, (
            "fused-epilogue soak retraced after warmup")
        assert eng.trace_counts == budget


class TestReviewRegressions:
    """Pinned repros from the ISSUE-14 review pass."""

    def test_greedy_argmax_survives_temperature_scale_collision(self):
        """A greedy row's /1e-6 temperature scaling is monotone but
        NOT injective: two adjacent fp32 logits can collide into one
        scaled value, and an argmax taken on the SCALED row would
        flip to the earlier index.  The kernel must argmax the raw
        fp32 logits, like the reference."""
        a = np.float32(1.5611286e-06)
        b = np.nextafter(a, np.float32(1.0))       # adjacent, larger
        assert b > a
        assert np.float32(a / np.float32(1e-6)) == \
            np.float32(b / np.float32(1e-6)), "repro precondition"
        row = np.full((V,), -50.0, np.float32)
        row[5] = a                                  # earlier, smaller
        row[90] = b                                 # later, the argmax
        logits = jnp.asarray(row)[None, :]
        keys = jax.vmap(jax.random.PRNGKey)(jnp.zeros(1, jnp.uint32))
        z = jnp.zeros((1,), jnp.float32)
        got = fused_sample(logits, keys, z, jnp.zeros((1,), jnp.int32),
                           z, implementation="pallas_interpret")
        assert int(got[0]) == 90
        ref = fused_sample_reference(logits, keys, z,
                                     jnp.zeros((1,), jnp.int32), z, V)
        assert int(ref[0]) == 90

    def test_released_slots_filter_params_are_masked(self):
        """``release_slot`` only clears the active bit — the engines
        must neutralize a released slot's stale top_k/top_p before the
        epilogue call, or the runtime sort short-circuit never fires
        again after the first sampled tenant."""
        from apex_tpu.serving import cache as slot_cache
        from apex_tpu.serving.engine import _active_sampling_params

        state = slot_cache.init_slot_state(3)
        state = slot_cache.admit_slot(
            state, jnp.int32(1), jnp.int32(7), jnp.int32(4),
            jnp.float32(0.9), jnp.int32(40), jnp.float32(0.9),
            jnp.int32(-1), jnp.uint32(0))
        temp, tk, tp = _active_sampling_params(state)
        assert int(tk[1]) == 40 and float(tp[1]) == pytest.approx(0.9)
        state = slot_cache.release_slot(state, jnp.int32(1))
        temp, tk, tp = _active_sampling_params(state)
        assert not bool(jnp.any(tk > 0))
        assert not bool(jnp.any((tp > 0.0) & (tp < 1.0)))

    def test_tuner_refuses_out_of_envelope_geometry(self, tmp_path,
                                                    monkeypatch):
        """An out-of-envelope sweep (vocab % 128 != 0) must cache
        NOTHING — every candidate would silently time the XLA
        reference, not the kernel."""
        monkeypatch.setenv("APEX_TPU_AUTOTUNE_CACHE",
                           str(tmp_path / "at.json"))
        autotune.clear_cache()
        try:
            best = autotune.tune_fused_sampling(
                n_rows=4, width=1000, sample_width=1,
                candidates=(128, 256),
                implementation="pallas_interpret")
            assert best is None
            assert autotune.cached_sampling_tile(1000, 1) is None
        finally:
            autotune.clear_cache()
