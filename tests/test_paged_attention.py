"""ops.paged_attention — block-table-gathered decode attention.

Contracts under test:

- the Pallas kernel (interpret mode — hermetic on CPU) is numerically
  identical to the XLA gather reference across decode (s=1) and chunk
  queries, GQA head ratios, ragged per-row lengths, and bf16;
- the computation depends only on the LOGICAL cache content: permuting
  the physical placement (new block tables, same logical pages) and
  poisoning every unallocated pool block with garbage must not change
  a single output bit — the position mask makes non-live pool content
  unreachable (the null-page invariant the serving engine relies on);
- the paged reference reproduces the dense cache attention of
  ``models/transformer.py`` on the same K/V (the greedy-parity anchor
  between the paged and dense serving engines);
- cost-analysis: the compiled per-step bytes of the paged path scale
  with LIVE pages while the dense cache einsum's bytes are pinned at
  ``max_seq_len`` regardless of how little of the cache is live (the
  PR-3-style bytes assertion for the serving datapath; the analytic
  model lives in ``bench_configs._serving_traffic_model``);
- quantized KV pages (ISSUE 8): the in-register-dequant Pallas kernel
  against the explicit quantize-dequant XLA reference (decode, GQA,
  ragged, spec-verify chunk, interpret mode), page+scale placement /
  pool-garbage invariance, the stated quantization-error bound vs the
  float pool, and the scale-argument validation contract.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu.ops.paged_attention import (
    kv_quant_spec,
    paged_attention,
    paged_attention_reference,
    quantize_kv_pages,
)

_KV_DTYPES = [
    "int8",
    pytest.param("fp8", marks=pytest.mark.skipif(
        not hasattr(jnp, "float8_e4m3fn"),
        reason="no float8_e4m3fn in this jax build")),
]


def _pool_setup(rng, *, b, hk, d, NB, BS, MB, lengths, s, dtype):
    """Random pool + per-row tables covering ``lengths[i] + s`` tokens
    with disjoint physical blocks (block 0 left as the null page)."""
    kp = jnp.asarray(rng.normal(size=(hk, NB, BS, d)), dtype)
    vp = jnp.asarray(rng.normal(size=(hk, NB, BS, d)), dtype)
    tables = np.zeros((b, MB), np.int32)
    free = list(range(1, NB))
    for i, L in enumerate(lengths):
        n = -(-(L + s) // BS)
        assert n <= MB and len(free) >= n, "test pool too small"
        for j in range(n):
            tables[i, j] = free.pop()
    return kp, vp, tables


class TestGoldenKernel:
    @pytest.mark.parametrize("s,h,hk,dtype", [
        (1, 4, 4, jnp.float32),        # pure decode, MHA
        (1, 8, 2, jnp.float32),        # decode, GQA 4:1
        (4, 4, 2, jnp.float32),        # chunk queries, GQA
        (4, 4, 4, jnp.bfloat16),       # chunk, bf16
    ])
    def test_kernel_matches_reference(self, s, h, hk, dtype):
        rng = np.random.default_rng(0)
        b, d, NB, BS, MB = 3, 32, 24, 8, 6
        lengths = [9, 0, 27]
        kp, vp, tables = _pool_setup(
            rng, b=b, hk=hk, d=d, NB=NB, BS=BS, MB=MB,
            lengths=lengths, s=s, dtype=dtype)
        q = jnp.asarray(rng.normal(size=(b, s, h, d)), dtype)
        lens = jnp.asarray(lengths, jnp.int32)
        ref = paged_attention_reference(q, kp, vp,
                                        jnp.asarray(tables), lens)
        out = paged_attention(q, kp, vp, jnp.asarray(tables), lens,
                              implementation="pallas_interpret")
        tol = 2e-2 if dtype == jnp.bfloat16 else 2e-5
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32),
            atol=tol, rtol=tol)

    def test_explicit_xla_matches_auto_on_cpu(self):
        rng = np.random.default_rng(1)
        b, s, h, hk, d, NB, BS, MB = 2, 1, 2, 2, 16, 10, 8, 4
        kp, vp, tables = _pool_setup(
            rng, b=b, hk=hk, d=d, NB=NB, BS=BS, MB=MB,
            lengths=[5, 11], s=s, dtype=jnp.float32)
        q = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
        lens = jnp.asarray([5, 11], jnp.int32)
        auto = paged_attention(q, kp, vp, jnp.asarray(tables), lens)
        xla = paged_attention(q, kp, vp, jnp.asarray(tables), lens,
                              implementation="xla")
        np.testing.assert_array_equal(np.asarray(auto),
                                      np.asarray(xla))


class TestLogicalContentOnly:
    """Outputs are a function of the logical cache, never of physical
    placement or non-live pool garbage."""

    @pytest.mark.parametrize("impl", ["xla", "pallas_interpret"])
    def test_placement_and_garbage_invariance(self, impl):
        rng = np.random.default_rng(2)
        b, s, h, hk, d, NB, BS, MB = 2, 2, 4, 2, 16, 30, 8, 5
        lengths = [10, 3]
        kp, vp, tables = _pool_setup(
            rng, b=b, hk=hk, d=d, NB=NB, BS=BS, MB=MB,
            lengths=lengths, s=s, dtype=jnp.float32)
        q = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
        lens = jnp.asarray(lengths, jnp.int32)
        base = paged_attention(q, kp, vp, jnp.asarray(tables), lens,
                               implementation=impl)

        # migrate every live page to a fresh physical block and poison
        # everything else (incl. the old homes and the null page)
        live = sorted({int(t) for t in tables.ravel() if t})
        dest = {blk: i + 1 for i, blk in enumerate(live)}
        assert not (set(dest.values()) & set(live))
        kp2 = np.asarray(rng.normal(size=(hk, NB, BS, d)),
                         np.float32) * 1e3
        vp2 = np.asarray(rng.normal(size=(hk, NB, BS, d)),
                         np.float32) * 1e3
        for src, dst in dest.items():
            kp2[:, dst] = np.asarray(kp[:, src])
            vp2[:, dst] = np.asarray(vp[:, src])
        tables2 = np.where(tables > 0,
                           np.vectorize(lambda t: dest.get(t, 0))(
                               tables), 0).astype(np.int32)
        moved = paged_attention(
            q, jnp.asarray(kp2), jnp.asarray(vp2),
            jnp.asarray(tables2), lens, implementation=impl)
        np.testing.assert_array_equal(np.asarray(base),
                                      np.asarray(moved))


class TestSpeculativeVerifyChunk:
    """The multi-query verify path (ISSUE 7): one ``s = 1 + k``
    application scores a draft run with per-position context identical
    to k+1 sequential one-token steps, and a REJECTED tail's stale
    K/V — live pages past a rolled-back cursor — is unreachable."""

    @pytest.mark.parametrize("impl", ["xla", "pallas_interpret"])
    def test_verify_chunk_matches_sequential_decode(self, impl):
        rng = np.random.default_rng(7)
        b, h, hk, d, NB, BS, MB, k = 2, 4, 2, 16, 24, 8, 6, 3
        lengths = [9, 17]
        kp, vp, tables = _pool_setup(
            rng, b=b, hk=hk, d=d, NB=NB, BS=BS, MB=MB,
            lengths=lengths, s=1 + k, dtype=jnp.float32)
        q = jnp.asarray(rng.normal(size=(b, 1 + k, h, d)), jnp.float32)
        lens = jnp.asarray(lengths, jnp.int32)
        chunk = paged_attention(q, kp, vp, jnp.asarray(tables), lens,
                                implementation=impl)
        # sequential: query j alone at its own position (the pool
        # already holds every draft's K/V — write-then-attend)
        for j in range(1 + k):
            one = paged_attention(
                q[:, j:j + 1], kp, vp, jnp.asarray(tables), lens + j,
                implementation=impl)
            np.testing.assert_allclose(
                np.asarray(chunk[:, j]), np.asarray(one[:, 0]),
                atol=2e-6, rtol=2e-6)

    def test_rejected_tail_garbage_is_unreachable(self):
        """Rollback contract: after the engine rejects a draft tail,
        its K/V stays in LIVE pages past the new cursor — the next
        step's queries must not see it.  Poison those positions; the
        masked output must not change a bit."""
        rng = np.random.default_rng(8)
        b, h, hk, d, NB, BS, MB = 1, 4, 2, 16, 16, 8, 4
        L = 10                     # cursor after rolling 3 drafts back
        kp, vp, tables = _pool_setup(
            rng, b=b, hk=hk, d=d, NB=NB, BS=BS, MB=MB,
            lengths=[L], s=1, dtype=jnp.float32)
        q = jnp.asarray(rng.normal(size=(b, 1, h, d)), jnp.float32)
        lens = jnp.asarray([L], jnp.int32)
        base = paged_attention(q, kp, vp, jnp.asarray(tables), lens,
                               implementation="xla")
        kp2, vp2 = np.asarray(kp).copy(), np.asarray(vp).copy()
        blk, off = tables[0, (L + 1) // BS], (L + 1) % BS
        kp2[:, blk, off:] = 1e3    # stale draft K/V in the live page
        vp2[:, blk, off:] = 1e3
        poisoned = paged_attention(
            q, jnp.asarray(kp2), jnp.asarray(vp2),
            jnp.asarray(tables), lens, implementation="xla")
        np.testing.assert_array_equal(np.asarray(base),
                                      np.asarray(poisoned))


class TestQuantizedKernel:
    """Quantized KV pages (ISSUE 8): int8/fp8 codes + per-(kv_head,
    page) fp32 amax scales.  The explicit quantize-dequant XLA
    reference is the parity anchor; the Pallas kernel dequantizes
    in-register (the per-page scale factors out of both contractions)
    and must agree to the same fp32-noise tolerance the unquantized
    golden suite uses — the two paths share the online-softmax
    algebra, only the dequant site differs."""

    @pytest.mark.parametrize("kv_dtype", _KV_DTYPES)
    @pytest.mark.parametrize("s,h,hk", [
        (1, 4, 4),        # pure decode, MHA
        (1, 8, 2),        # decode, GQA 4:1
        (4, 4, 2),        # chunk queries (spec-verify shape), GQA
    ])
    def test_kernel_matches_quant_dequant_reference(self, s, h, hk,
                                                    kv_dtype):
        rng = np.random.default_rng(10)
        b, d, NB, BS, MB = 3, 32, 24, 8, 6
        lengths = [9, 0, 27]
        kp, vp, tables = _pool_setup(
            rng, b=b, hk=hk, d=d, NB=NB, BS=BS, MB=MB,
            lengths=lengths, s=s, dtype=jnp.float32)
        kq, vq, ks, vs = quantize_kv_pages(kp, vp, kv_dtype)
        q = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
        lens = jnp.asarray(lengths, jnp.int32)
        ref = paged_attention_reference(
            q, kq, vq, jnp.asarray(tables), lens,
            k_scales=ks, v_scales=vs)
        out = paged_attention(
            q, kq, vq, jnp.asarray(tables), lens,
            k_scales=ks, v_scales=vs,
            implementation="pallas_interpret")
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32),
            atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("kv_dtype", _KV_DTYPES)
    def test_explicit_xla_matches_auto_on_cpu(self, kv_dtype):
        """On CPU a quantized pool auto-dispatches to the reference:
        bitwise."""
        rng = np.random.default_rng(11)
        b, s, h, hk, d, NB, BS, MB = 2, 1, 2, 2, 16, 10, 8, 4
        kp, vp, tables = _pool_setup(
            rng, b=b, hk=hk, d=d, NB=NB, BS=BS, MB=MB,
            lengths=[5, 11], s=s, dtype=jnp.float32)
        kq, vq, ks, vs = quantize_kv_pages(kp, vp, kv_dtype)
        q = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
        lens = jnp.asarray([5, 11], jnp.int32)
        auto = paged_attention(q, kq, vq, jnp.asarray(tables), lens,
                               k_scales=ks, v_scales=vs)
        xla = paged_attention(q, kq, vq, jnp.asarray(tables), lens,
                              k_scales=ks, v_scales=vs,
                              implementation="xla")
        np.testing.assert_array_equal(np.asarray(auto),
                                      np.asarray(xla))

    @pytest.mark.parametrize("impl", ["xla", "pallas_interpret"])
    def test_placement_and_garbage_invariance(self, impl):
        """A page's SCALE travels with it: migrating live pages (and
        their scale entries) to fresh physical blocks while poisoning
        every dead block's codes AND scales must not change one output
        bit — the invariant that lets shared/CoW/preempted quantized
        pages move without rescaling."""
        rng = np.random.default_rng(12)
        b, s, h, hk, d, NB, BS, MB = 2, 2, 4, 2, 16, 30, 8, 5
        lengths = [10, 3]
        kp, vp, tables = _pool_setup(
            rng, b=b, hk=hk, d=d, NB=NB, BS=BS, MB=MB,
            lengths=lengths, s=s, dtype=jnp.float32)
        kq, vq, ks, vs = quantize_kv_pages(kp, vp, "int8")
        q = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
        lens = jnp.asarray(lengths, jnp.int32)
        base = paged_attention(q, kq, vq, jnp.asarray(tables), lens,
                               k_scales=ks, v_scales=vs,
                               implementation=impl)

        live = sorted({int(t) for t in tables.ravel() if t})
        dest = {blk: i + 1 for i, blk in enumerate(live)}
        assert not (set(dest.values()) & set(live))
        kq2 = np.asarray(rng.integers(-127, 128, size=(hk, NB, BS, d)),
                         np.int8)
        vq2 = np.asarray(rng.integers(-127, 128, size=(hk, NB, BS, d)),
                         np.int8)
        ks2 = np.asarray(rng.normal(size=(hk, NB)),
                         np.float32) * 1e3            # garbage scales
        vs2 = np.asarray(rng.normal(size=(hk, NB)), np.float32) * 1e3
        for src, dst in dest.items():
            kq2[:, dst] = np.asarray(kq[:, src])
            vq2[:, dst] = np.asarray(vq[:, src])
            ks2[:, dst] = np.asarray(ks[:, src])
            vs2[:, dst] = np.asarray(vs[:, src])
        tables2 = np.where(tables > 0,
                           np.vectorize(lambda t: dest.get(t, 0))(
                               tables), 0).astype(np.int32)
        moved = paged_attention(
            q, jnp.asarray(kq2), jnp.asarray(vq2),
            jnp.asarray(tables2), lens,
            k_scales=jnp.asarray(ks2), v_scales=jnp.asarray(vs2),
            implementation=impl)
        np.testing.assert_array_equal(np.asarray(base),
                                      np.asarray(moved))

    @pytest.mark.parametrize("impl", ["xla", "pallas_interpret"])
    def test_verify_chunk_matches_sequential_decode(self, impl):
        """The spec-verify chunk (s = 1+k) rides the quantized path
        unchanged: chunk positions == k+1 sequential decode steps over
        the same quantized pool."""
        rng = np.random.default_rng(13)
        b, h, hk, d, NB, BS, MB, k = 2, 4, 2, 16, 24, 8, 6, 3
        lengths = [9, 17]
        kp, vp, tables = _pool_setup(
            rng, b=b, hk=hk, d=d, NB=NB, BS=BS, MB=MB,
            lengths=lengths, s=1 + k, dtype=jnp.float32)
        kq, vq, ks, vs = quantize_kv_pages(kp, vp, "int8")
        q = jnp.asarray(rng.normal(size=(b, 1 + k, h, d)), jnp.float32)
        lens = jnp.asarray(lengths, jnp.int32)
        chunk = paged_attention(q, kq, vq, jnp.asarray(tables), lens,
                                k_scales=ks, v_scales=vs,
                                implementation=impl)
        for j in range(1 + k):
            one = paged_attention(
                q[:, j:j + 1], kq, vq, jnp.asarray(tables), lens + j,
                k_scales=ks, v_scales=vs, implementation=impl)
            np.testing.assert_allclose(
                np.asarray(chunk[:, j]), np.asarray(one[:, 0]),
                atol=2e-6, rtol=2e-6)

    @pytest.mark.parametrize("kv_dtype,bound", [
        ("int8", 0.05),
        pytest.param("fp8", 0.2, marks=pytest.mark.skipif(
            not hasattr(jnp, "float8_e4m3fn"),
            reason="no float8_e4m3fn in this jax build")),
    ])
    def test_error_vs_float_pool_within_stated_bound(self, kv_dtype,
                                                     bound):
        """The ISSUE-8 accuracy bound, stated: for unit-variance K/V,
        symmetric per-page amax quantization perturbs each element by
        at most scale/254 (int8 round-to-nearest) / one e4m3 ulp
        (~6% relative, fp8); through the softmax-weighted average the
        per-step attention output error stays under 0.05 (int8) /
        0.2 (fp8) absolute — measured ~0.02 / ~0.1 on this fixture,
        asserted at 2× headroom."""
        rng = np.random.default_rng(14)
        b, s, h, hk, d, NB, BS, MB = 3, 4, 8, 2, 32, 24, 8, 6
        lengths = [9, 0, 27]
        kp, vp, tables = _pool_setup(
            rng, b=b, hk=hk, d=d, NB=NB, BS=BS, MB=MB,
            lengths=lengths, s=s, dtype=jnp.float32)
        q = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
        lens = jnp.asarray(lengths, jnp.int32)
        base = paged_attention_reference(q, kp, vp,
                                         jnp.asarray(tables), lens)
        kq, vq, ks, vs = quantize_kv_pages(kp, vp, kv_dtype)
        quant = paged_attention_reference(
            q, kq, vq, jnp.asarray(tables), lens,
            k_scales=ks, v_scales=vs)
        err = np.abs(np.asarray(quant) - np.asarray(base)).max()
        assert err <= bound, (kv_dtype, err)

    def test_zero_pages_quantize_to_exact_zero(self):
        """An all-zero page (scale 0) must quantize AND dequantize to
        exact zeros — the near-zero guard, not NaN from 0 × inf."""
        kp = jnp.zeros((2, 4, 8, 16), jnp.float32)
        kq, vq, ks, vs = quantize_kv_pages(kp, kp, "int8")
        assert not np.asarray(kq).any()
        assert not np.asarray(ks).any()
        q = jnp.ones((1, 1, 2, 16), jnp.float32)
        out = paged_attention_reference(
            q, kq, vq, jnp.ones((1, 2), jnp.int32),
            jnp.asarray([9], jnp.int32), k_scales=ks, v_scales=vs)
        assert np.isfinite(np.asarray(out)).all()
        np.testing.assert_array_equal(np.asarray(out), 0.0)

    def test_scale_argument_validation(self):
        rng = np.random.default_rng(15)
        kp = jnp.asarray(rng.normal(size=(2, 4, 8, 16)), jnp.float32)
        kq, vq, ks, vs = quantize_kv_pages(kp, kp, "int8")
        q = jnp.zeros((1, 1, 2, 16), jnp.float32)
        tables = jnp.zeros((1, 2), jnp.int32)
        lens = jnp.zeros((1,), jnp.int32)
        with pytest.raises(ValueError, match="need k_scales"):
            paged_attention(q, kq, vq, tables, lens)
        with pytest.raises(ValueError, match="only apply"):
            paged_attention(q, kp, kp, tables, lens,
                            k_scales=ks, v_scales=vs)
        with pytest.raises(ValueError, match="k_scales shape"):
            paged_attention(q, kq, vq, tables, lens,
                            k_scales=ks[:, :2], v_scales=vs)
        with pytest.raises(ValueError, match="dtypes differ"):
            paged_attention(q, kq, vq.astype(jnp.float32), tables,
                            lens, k_scales=ks, v_scales=vs)

    def test_kv_quant_spec_contract(self):
        assert kv_quant_spec(None) == (None, None)
        dt, qmax = kv_quant_spec("int8")
        assert jnp.dtype(dt) == jnp.dtype(jnp.int8) and qmax == 127.0
        with pytest.raises(ValueError, match="kv_dtype"):
            kv_quant_spec("int4")
        if hasattr(jnp, "float8_e4m3fn"):
            dt, qmax = kv_quant_spec("fp8")
            assert qmax == 448.0
        with pytest.raises(ValueError, match="int8"):
            quantize_kv_pages(jnp.zeros((1, 2, 8, 8)),
                              jnp.zeros((1, 2, 8, 8)), None)


class TestDenseParityAnchor:
    def test_reference_matches_dense_cache_attention(self):
        """Paged reference == the dense engine's cache attention on
        the same logical K/V (shared-length rows, s=1): the numerics
        bridge behind engine-level greedy parity."""
        from apex_tpu.models.transformer import _cache_attention

        rng = np.random.default_rng(3)
        b, h, hk, d, BS = 2, 4, 2, 16, 8
        S = 32                     # dense cache length == MB * BS
        MB = S // BS
        NB = b * MB + 1
        L = 19                     # shared live length (scalar idx)
        dense_k = jnp.asarray(rng.normal(size=(b, S, hk, d)),
                              jnp.float32)
        dense_v = jnp.asarray(rng.normal(size=(b, S, hk, d)),
                              jnp.float32)
        q = jnp.asarray(rng.normal(size=(b, 1, h, d)), jnp.float32)
        # pack the dense rows into pool pages
        kp = np.zeros((hk, NB, BS, d), np.float32)
        vp = np.zeros((hk, NB, BS, d), np.float32)
        tables = np.zeros((b, MB), np.int32)
        nxt = 1
        for i in range(b):
            for j in range(MB):
                kp[:, nxt] = np.asarray(
                    dense_k[i, j * BS:(j + 1) * BS]).transpose(1, 0, 2)
                vp[:, nxt] = np.asarray(
                    dense_v[i, j * BS:(j + 1) * BS]).transpose(1, 0, 2)
                tables[i, j] = nxt
                nxt += 1
        scale = d ** -0.5
        dense = _cache_attention(q, dense_k, dense_v,
                                 jnp.int32(L), scale)
        paged = paged_attention_reference(
            q, jnp.asarray(kp), jnp.asarray(vp), jnp.asarray(tables),
            jnp.full((b,), L, jnp.int32), scale=scale)
        np.testing.assert_allclose(np.asarray(paged),
                                   np.asarray(dense), atol=1e-5,
                                   rtol=1e-5)


class TestValidation:
    def test_shape_mismatches_raise(self):
        q = jnp.zeros((2, 1, 4, 16))
        kp = jnp.zeros((2, 4, 8, 16))
        tables = jnp.zeros((2, 2), jnp.int32)
        lens = jnp.zeros((2,), jnp.int32)
        with pytest.raises(ValueError, match="head_dim"):
            paged_attention(q, jnp.zeros((2, 4, 8, 8)),
                            jnp.zeros((2, 4, 8, 8)), tables, lens)
        with pytest.raises(ValueError, match="divide"):
            paged_attention(jnp.zeros((2, 1, 3, 16)), kp, kp,
                            tables, lens)
        with pytest.raises(ValueError, match="batch"):
            paged_attention(q, kp, kp, tables,
                            jnp.zeros((3,), jnp.int32))
        with pytest.raises(ValueError, match="differ"):
            paged_attention(q, kp, jnp.zeros((2, 5, 8, 16)),
                            tables, lens)


class TestAutotune:
    def test_sweep_caches_under_the_engine_lookup_key(
            self, tmp_path, monkeypatch):
        """tune_paged_attention must produce an entry the engine's
        ``block_size=0`` lookup actually finds: keyed on head_dim +
        dtype, pool auto-sized to the sweep (regression: the original
        fixed pool made every candidate raise, silently caching
        nothing)."""
        monkeypatch.setenv("APEX_TPU_AUTOTUNE_CACHE",
                           str(tmp_path / "at.json"))
        from apex_tpu.ops import autotune

        autotune.clear_cache()
        try:
            # kv_dtypes=(None,) = the pre-ISSUE-8 sweep, unchanged
            best, kvd = autotune.tune_paged_attention(
                n_rows=2, width=16, kv_heads=2, live_tokens=64,
                dtype="float32", candidates=(8, 16),
                kv_dtypes=(None,))
            assert best in (8, 16) and kvd is None
            autotune.clear_cache()     # force a reload from the file
            assert autotune.cached_block_rows(
                "paged_attention", 16,
                str(jnp.dtype("float32")), kv_heads=2) == best
            # entries are kv-head-qualified (ISSUE 13): a TP engine
            # querying with its per-shard count must NOT find the
            # full-head-count winner
            assert autotune.cached_block_rows(
                "paged_attention", 16,
                str(jnp.dtype("float32")), kv_heads=1) is None
            assert autotune.cached_block_rows(
                "paged_attention", 16,
                str(jnp.dtype("float32"))) is None
        finally:
            autotune.clear_cache()     # drop the tmp-file cache state

    def test_joint_kv_dtype_sweep_caches_pair_and_per_dtype_entries(
            self, tmp_path, monkeypatch):
        """The ISSUE-8 joint sweep: every storage dtype gets a
        block-size entry under ITS key (the engine's explicit-kv_dtype
        lookup), and the winning (block, kv_dtype) pair lands under
        the compute-dtype pair key that kv_dtype='auto' consults."""
        monkeypatch.setenv("APEX_TPU_AUTOTUNE_CACHE",
                           str(tmp_path / "at.json"))
        from apex_tpu.ops import autotune

        autotune.clear_cache()
        try:
            pair = autotune.tune_paged_attention(
                n_rows=2, width=16, kv_heads=2, live_tokens=64,
                dtype="float32", candidates=(8, 16),
                kv_dtypes=(None, "int8"))
            assert pair is not None
            bs, kvd = pair
            assert bs in (8, 16) and kvd in (None, "int8")
            autotune.clear_cache()
            assert autotune.cached_block_rows(
                "paged_attention", 16, "float32", kv_heads=2) in (8, 16)
            assert autotune.cached_block_rows(
                "paged_attention", 16, "int8", kv_heads=2) in (8, 16)
            assert autotune.cached_paged_pair(
                16, "float32", kv_heads=2) == pair
            # untuned (device, width, dtype, kv_heads) stays a miss —
            # incl. the same width at a different (per-shard) head
            # count
            assert autotune.cached_paged_pair(
                32, "float32", kv_heads=2) is None
            assert autotune.cached_paged_pair(
                16, "float32", kv_heads=1) is None
        finally:
            autotune.clear_cache()


class TestPerStepBytesScaleWithLiveTokens:
    """The paged datapath's cost-model bytes grow with LIVE pages; the
    dense cache einsum reads the full ``max_seq_len`` slab per step no
    matter how little is live (the measured defect the paged tentpole
    fixes — documented in ``bench_configs._serving_traffic_model``)."""

    def _bytes(self, fn, *args):
        lowered = jax.jit(fn).lower(*args)
        ca = lowered.compile().cost_analysis()
        if isinstance(ca, list):            # older jax: per-computation
            ca = ca[0]
        if not ca or "bytes accessed" not in ca:
            pytest.skip("cost_analysis without bytes on this backend")
        return float(ca["bytes accessed"])

    def test_paged_bytes_track_live_pages_dense_bytes_do_not(self):
        from apex_tpu.models.transformer import _cache_attention

        rng = np.random.default_rng(4)
        b, h, hk, d, BS = 2, 4, 4, 64, 16
        S = 512                              # dense slab length
        NB = 2 * (S // BS) + 1
        kp = jnp.asarray(rng.normal(size=(hk, NB, BS, d)), jnp.float32)
        vp = jnp.asarray(rng.normal(size=(hk, NB, BS, d)), jnp.float32)
        q = jnp.asarray(rng.normal(size=(b, 1, h, d)), jnp.float32)
        dense_k = jnp.asarray(rng.normal(size=(b, S, hk, d)),
                              jnp.float32)
        dense_v = jnp.asarray(rng.normal(size=(b, S, hk, d)),
                              jnp.float32)

        def paged_at(mb):
            tables = jnp.asarray(
                np.arange(1, b * mb + 1).reshape(b, mb), jnp.int32)
            lens = jnp.full((b,), mb * BS - 1, jnp.int32)
            return self._bytes(
                lambda q: paged_attention_reference(
                    q, kp, vp, tables, lens), q)

        # live = 64 vs 256 tokens: paged bytes must scale ~linearly
        paged_small = paged_at(64 // BS)
        paged_big = paged_at(256 // BS)
        ratio = paged_big / paged_small
        assert 2.0 <= ratio <= 8.0, (paged_small, paged_big)

        def dense_at(live):
            idx = jnp.int32(live - 1)
            return self._bytes(
                lambda q: _cache_attention(q, dense_k, dense_v, idx,
                                           d ** -0.5), q)

        # the dense einsum's bytes are live-independent (the cursor
        # only masks) — THE defect: reads pinned at max_seq_len
        dense_small = dense_at(64)
        dense_big = dense_at(256)
        assert abs(dense_big - dense_small) / dense_big < 0.05, (
            dense_small, dense_big)
        # and at short live lengths the paged step reads far less than
        # the dense slab pass
        assert paged_small < 0.5 * dense_small, (paged_small,
                                                 dense_small)


# --------------------------------------------------------------------- #
# fused decode prologue (ISSUE 14) — RoPE + write + attend in one op
# --------------------------------------------------------------------- #
class TestFusedDecodePrologue:
    """``paged_decode_fused``: the width-1 decode step's prologue
    (per-row RoPE → [quantize] → page write) folded into the attend.

    Both sides run under jit (the only way the engines run them): the
    reference must be the historical unfused sequence verbatim, and
    the interpret-mode kernel must reproduce the reference's written
    pages / codes / scales BITWISE on live pages (the null page stays
    garbage-by-contract on every path) with the attend output equal up
    to the kernel's blocked accumulation order."""

    def _setup(self, rng, *, b=3, h=8, hk=4, d=32, BS=8, S=64,
               kv_dtype=None, lengths=None):
        from apex_tpu.ops.paged_attention import quantize_kv_pages
        from apex_tpu.ops.rope import rope_cos_sin

        MB = S // BS
        NB = b * MB + 3
        kp = jnp.asarray(rng.normal(size=(hk, NB, BS, d)), jnp.float32)
        vp = jnp.asarray(rng.normal(size=(hk, NB, BS, d)), jnp.float32)
        scales = {}
        if kv_dtype is not None:
            kp, vp, ks, vs = quantize_kv_pages(kp, vp, kv_dtype)
            scales = dict(k_scales=ks, v_scales=vs,
                          chunk_lens=jnp.ones((b,), jnp.int32))
        if lengths is None:
            # fresh-page, mid-page and page-boundary-append rows
            lengths = np.array([5, BS, 3 * BS - 1], np.int32)[:b]
        tables = np.zeros((b, MB), np.int32)
        used = rng.permutation(np.arange(1, NB))[: b * MB] \
            .reshape(b, MB)
        for r in range(b):
            npages = min(MB, -(-int(min(lengths[r], S - 1) + 1) // BS))
            tables[r, :npages] = used[r, :npages]
        q = jnp.asarray(rng.normal(size=(b, 1, h, d)), jnp.float32)
        nk = jnp.asarray(rng.normal(size=(b, 1, hk, d)), jnp.float32)
        nv = jnp.asarray(rng.normal(size=(b, 1, hk, d)), jnp.float32)
        cos, sin = rope_cos_sin(S, d)
        pc = np.minimum(lengths[:, None], S - 1)
        rope = dict(cos_b=jnp.asarray(cos[pc][:, :, None, :]),
                    sin_b=jnp.asarray(sin[pc][:, :, None, :]))
        live = tables.ravel()
        return (q, nk, nv, kp, vp, jnp.asarray(tables),
                jnp.asarray(lengths), rope, scales, S,
                live[live > 0])

    @staticmethod
    def _run(impl, args, S, rope, scales):
        from apex_tpu.ops.paged_attention import paged_decode_fused
        return jax.jit(lambda *a: paged_decode_fused(
            *a, max_seq_len=S, implementation=impl, **rope,
            **scales))(*args)

    def test_reference_is_the_unfused_sequence(self):
        """XLA reference == rope_rows → scatter → gather-attend,
        composed by hand from the same public pieces — bitwise."""
        from apex_tpu.ops.paged_attention import (
            paged_attention_reference, paged_decode_fused_reference,
            rope_rows)

        rng = np.random.default_rng(3)
        (q, nk, nv, kp, vp, tables, lengths, rope, _sc, S,
         _live) = self._setup(rng)
        got = jax.jit(lambda *a: paged_decode_fused_reference(
            *a, max_seq_len=S, **rope))(
            q, nk, nv, kp, vp, tables, lengths)

        def manual(q, nk, nv, kp, vp, tables, lengths):
            BS, MB = kp.shape[2], tables.shape[1]
            qm = rope_rows(q, rope["cos_b"], rope["sin_b"])
            km = rope_rows(nk, rope["cos_b"], rope["sin_b"])
            pos = lengths[:, None]
            phys = jnp.take_along_axis(
                tables, jnp.minimum(pos // BS, MB - 1), axis=1)
            phys = jnp.where(pos < S, phys, 0)
            off = pos % BS
            kp = kp.at[:, phys, off].set(km.transpose(2, 0, 1, 3))
            vp = vp.at[:, phys, off].set(nv.transpose(2, 0, 1, 3))
            return (paged_attention_reference(qm, kp, vp, tables,
                                              lengths), kp, vp)

        ref = jax.jit(manual)(q, nk, nv, kp, vp, tables, lengths)
        for a, b in zip(got, ref):
            np.testing.assert_array_equal(np.asarray(a),
                                          np.asarray(b))

    def test_kernel_matches_reference_unquantized(self):
        rng = np.random.default_rng(4)
        (q, nk, nv, kp, vp, tables, lengths, rope, sc, S,
         live) = self._setup(rng)
        args = (q, nk, nv, kp, vp, tables, lengths)
        ref = self._run("xla", args, S, rope, sc)
        got = self._run("pallas_interpret", args, S, rope, sc)
        np.testing.assert_allclose(np.asarray(got[0]),
                                   np.asarray(ref[0]),
                                   rtol=2e-5, atol=2e-5)
        # written pages bitwise on live pages (write-then-attend: the
        # new row IS in the returned pool)
        for i in (1, 2):
            np.testing.assert_array_equal(
                np.asarray(got[i][:, live]), np.asarray(ref[i][:, live]))

    @pytest.mark.parametrize("kv_dtype", _KV_DTYPES)
    def test_kernel_matches_reference_quantized(self, kv_dtype):
        """Codes AND monotone running-amax scales bitwise on live
        pages — the PR-8 scale discipline survives the fusion."""
        rng = np.random.default_rng(5)
        (q, nk, nv, kp, vp, tables, lengths, rope, sc, S,
         live) = self._setup(rng, kv_dtype=kv_dtype)
        args = (q, nk, nv, kp, vp, tables, lengths)
        ref = self._run("xla", args, S, rope, sc)
        got = self._run("pallas_interpret", args, S, rope, sc)
        np.testing.assert_allclose(np.asarray(got[0]),
                                   np.asarray(ref[0]),
                                   rtol=2e-5, atol=2e-5)
        for i in (1, 2, 3, 4):
            np.testing.assert_array_equal(
                np.asarray(got[i][:, live]), np.asarray(ref[i][:, live]))

    def test_no_rope_model_is_fully_bitwise(self):
        """Learned-position models skip the rotation: the written row
        is a pure insert, so kernel pool output == reference pool
        output bit-for-bit on live pages."""
        rng = np.random.default_rng(6)
        (q, nk, nv, kp, vp, tables, lengths, _rope, sc, S,
         live) = self._setup(rng, kv_dtype="int8")
        args = (q, nk, nv, kp, vp, tables, lengths)
        ref = self._run("xla", args, S, {}, sc)
        got = self._run("pallas_interpret", args, S, {}, sc)
        for i in (1, 2, 3, 4):
            np.testing.assert_array_equal(
                np.asarray(got[i][:, live]), np.asarray(ref[i][:, live]))

    def test_past_max_seq_len_routes_to_null_page(self):
        """A cursor at/past max_seq_len writes the null page on both
        paths: every LIVE page must be byte-identical to its input
        (nothing live was touched)."""
        rng = np.random.default_rng(7)
        (q, nk, nv, kp, vp, tables, lengths, rope, sc, S,
         live) = self._setup(rng, lengths=np.array([64, 70, 5],
                                                   np.int32))
        args = (q, nk, nv, kp, vp, tables, lengths)
        for impl in ("xla", "pallas_interpret"):
            got = self._run(impl, args, S, rope, sc)
            # rows 0/1 nulled; row 2 wrote its page — all OTHER rows'
            # live pages unchanged
            row2 = set(np.asarray(tables)[2].tolist())
            untouched = [p for p in live.tolist() if p not in row2]
            np.testing.assert_array_equal(
                np.asarray(got[1][:, untouched]),
                np.asarray(kp[:, untouched]))

    def test_width_gt_one_raises(self):
        from apex_tpu.ops.paged_attention import paged_decode_fused

        rng = np.random.default_rng(8)
        (q, nk, nv, kp, vp, tables, lengths, rope, sc, S,
         _live) = self._setup(rng)
        q2 = jnp.concatenate([q, q], axis=1)
        nk2 = jnp.concatenate([nk, nk], axis=1)
        with pytest.raises(ValueError, match="width-1"):
            paged_decode_fused(q2, nk2, nk2, kp, vp, tables, lengths,
                               max_seq_len=S)

    def test_scale_argument_validation(self):
        from apex_tpu.ops.paged_attention import paged_decode_fused
        from apex_tpu.ops.paged_attention import quantize_kv_pages

        rng = np.random.default_rng(9)
        (q, nk, nv, kp, vp, tables, lengths, rope, _sc, S,
         _live) = self._setup(rng)
        kq, vq, ks, vs = quantize_kv_pages(kp, vp, "int8")
        with pytest.raises(ValueError, match="need k_scales"):
            paged_decode_fused(q, nk, nv, kq, vq, tables, lengths,
                               max_seq_len=S)
        with pytest.raises(ValueError, match="only apply"):
            paged_decode_fused(q, nk, nv, kp, vp, tables, lengths,
                               max_seq_len=S, k_scales=ks, v_scales=vs)
