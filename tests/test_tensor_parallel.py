"""Hermetic tensor-parallel tests on an 8-virtual-device CPU mesh —
strictly better than the reference's >=2-real-GPU requirement
(SURVEY.md §4): TP layer math vs dense reference, mapping dualities,
vocab-parallel CE vs full-vocab CE, sequence parallelism."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu.core import mesh as mesh_lib
from apex_tpu.transformer import (
    mappings,
    column_parallel_linear,
    row_parallel_linear,
    vocab_parallel_embedding,
    vocab_parallel_cross_entropy,
    parallel_state,
)
from apex_tpu import ops


@pytest.fixture
def tp_mesh():
    m = mesh_lib.initialize_mesh(tensor_model_parallel_size=4,
                                 data_parallel_size=2)
    yield m
    mesh_lib.destroy_mesh()


def shard_map(fn, mesh, in_specs, out_specs, **kw):
    kw.setdefault("check_vma", False)
    return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, **kw)


def _smap(mesh, fn, in_specs, out_specs):
    return shard_map(fn, mesh, in_specs, out_specs)


class TestMappings:
    def test_copy_and_reduce_duality(self, tp_mesh, rng):
        x = jnp.asarray(rng.normal(size=(8, 16)), jnp.float32)

        # f: identity fwd
        f = _smap(tp_mesh, lambda x: mappings.copy_to_tensor_parallel_region(x),
                  (P(),), P())
        np.testing.assert_allclose(np.asarray(f(x)), np.asarray(x))

        # f bwd: grad of sum over all shards' use = psum of ones = tp_size
        def loss(x):
            y = _smap(tp_mesh,
                      lambda x: mappings.copy_to_tensor_parallel_region(x),
                      (P(),), P())(x)
            return jnp.sum(y)
        g = jax.grad(loss)(x)
        # single logical consumer -> grad == tp_size (psum over 4 ranks)
        np.testing.assert_allclose(np.asarray(g), 4.0)

    def test_reduce_from_sums_partials(self, tp_mesh):
        # each shard contributes its rank; psum = 0+1+2+3 = 6
        def body():
            r = lax.axis_index("tensor").astype(jnp.float32)
            return mappings.reduce_from_tensor_parallel_region(
                jnp.full((2, 2), r))
        f = _smap(tp_mesh, body, (), P())
        np.testing.assert_allclose(np.asarray(f()), 6.0)

    def test_scatter_gather_roundtrip(self, tp_mesh, rng):
        x = jnp.asarray(rng.normal(size=(4, 16)), jnp.float32)

        def body(x):
            s = mappings.scatter_to_tensor_parallel_region(x)
            return mappings.gather_from_tensor_parallel_region(s)
        f = _smap(tp_mesh, body, (P(),), P())
        np.testing.assert_allclose(np.asarray(f(x)), np.asarray(x))

    def test_sequence_parallel_pair(self, tp_mesh, rng):
        # gather(seq) then reduce_scatter(seq) over partials == psum/g…
        x = jnp.asarray(rng.normal(size=(8, 4)), jnp.float32)

        def body(xs):
            full = mappings.gather_from_sequence_parallel_region(xs, dim=0)
            return mappings.reduce_scatter_to_sequence_parallel_region(
                full, dim=0)
        f = _smap(tp_mesh, body, (P("tensor", None),), P("tensor", None))
        # gather makes (8,4) full on each rank; reduce-scatter sums the
        # 4 identical copies and hands back this rank's slice → 4*x
        np.testing.assert_allclose(np.asarray(f(x)), 4 * np.asarray(x),
                                   rtol=1e-6)


class TestTPLinearFunctions:
    def test_column_then_row_matches_dense(self, tp_mesh, rng):
        b, din, dmid, dout = 4, 16, 32, 24
        x = jnp.asarray(rng.normal(size=(b, din)), jnp.float32)
        w1 = jnp.asarray(rng.normal(size=(din, dmid)), jnp.float32)
        w2 = jnp.asarray(rng.normal(size=(dmid, dout)), jnp.float32)

        def block(x, w1s, w2s):
            h = column_parallel_linear(x, w1s)
            h = jax.nn.relu(h)
            return row_parallel_linear(h, w2s)

        f = _smap(tp_mesh, block,
                  (P(), P(None, "tensor"), P("tensor", None)), P())
        got = f(x, w1, w2)
        want = jax.nn.relu(x @ w1) @ w2
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    @pytest.mark.l0
    def test_grads_match_dense(self, tp_mesh, rng):
        # canonical shard_map TP training pattern: the per-shard loss is
        # the FULL loss (output replicated after reduce_from); grads are
        # taken inside the region, and the mappings' custom VJPs insert
        # the collectives (copy_to bwd = psum) — Megatron semantics.
        b, din, dmid = 4, 8, 16
        x = jnp.asarray(rng.normal(size=(b, din)), jnp.float32)
        w1 = jnp.asarray(rng.normal(size=(din, dmid)), jnp.float32)
        w2 = jnp.asarray(rng.normal(size=(dmid, din)), jnp.float32)

        def per_shard_grads(x, w1s, w2s):
            def loss_fn(w1s, w2s):
                h = jax.nn.relu(column_parallel_linear(x, w1s))
                y = row_parallel_linear(h, w2s)
                return jnp.sum(y ** 2)
            return jax.grad(loss_fn, argnums=(0, 1))(w1s, w2s)

        f = _smap(tp_mesh, per_shard_grads,
                  (P(), P(None, "tensor"), P("tensor", None)),
                  (P(None, "tensor"), P("tensor", None)))
        g_tp = f(x, w1, w2)

        def dense_loss(w1, w2):
            return jnp.sum((jax.nn.relu(x @ w1) @ w2) ** 2)

        g_d = jax.grad(dense_loss, argnums=(0, 1))(w1, w2)
        for a, b2 in zip(g_tp, g_d):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b2),
                                       rtol=1e-5, atol=1e-5)

    def test_sequence_parallel_block_matches_dense(self, tp_mesh, rng):
        # SP: activations sharded along sequence between blocks
        s, din, dmid = 8, 16, 32
        x = jnp.asarray(rng.normal(size=(s, din)), jnp.float32)
        w1 = jnp.asarray(rng.normal(size=(din, dmid)), jnp.float32)
        w2 = jnp.asarray(rng.normal(size=(dmid, din)), jnp.float32)

        def block(xs, w1s, w2s):
            h = column_parallel_linear(xs, w1s, sequence_parallel=True,
                                       seq_dim=0)
            h = jax.nn.relu(h)
            return row_parallel_linear(h, w2s, sequence_parallel=True,
                                       seq_dim=0)

        f = _smap(tp_mesh, block,
                  (P("tensor", None), P(None, "tensor"),
                   P("tensor", None)),
                  P("tensor", None))
        got = f(x, w1, w2)
        want = jax.nn.relu(x @ w1) @ w2
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)


class TestVocabParallel:
    def test_embedding_matches_dense(self, tp_mesh, rng):
        vocab, dim = 64, 8
        table = jnp.asarray(rng.normal(size=(vocab, dim)), jnp.float32)
        ids = jnp.asarray(rng.integers(0, vocab, size=(4, 6)))
        f = _smap(tp_mesh,
                  lambda i, t: vocab_parallel_embedding(i, t),
                  (P(), P("tensor", None)), P())
        got = f(ids, table)
        want = jnp.take(table, ids, axis=0)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want))

    @pytest.mark.parametrize("smoothing", [0.0, 0.1])
    def test_cross_entropy_matches_full_vocab(self, tp_mesh, rng,
                                              smoothing):
        n, vocab = 8, 64
        logits = jnp.asarray(rng.normal(size=(n, vocab)), jnp.float32) * 3
        labels = jnp.asarray(rng.integers(0, vocab, size=(n,)))
        f = _smap(tp_mesh,
                  lambda l, t: vocab_parallel_cross_entropy(
                      l, t, smoothing=smoothing),
                  (P(None, "tensor"), P()), P())
        got = f(logits, labels)
        want = ops.softmax_cross_entropy(logits, labels, smoothing)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    def test_cross_entropy_grads_match(self, tp_mesh, rng):
        n, vocab = 4, 32
        logits = jnp.asarray(rng.normal(size=(n, vocab)), jnp.float32)
        labels = jnp.asarray(rng.integers(0, vocab, size=(n,)))

        def per_shard_grad(l, t):
            return jax.grad(lambda l: jnp.mean(
                vocab_parallel_cross_entropy(l, t)))(l)

        g_tp = _smap(tp_mesh, per_shard_grad,
                     (P(None, "tensor"), P()), P(None, "tensor"))(
            logits, labels)

        def full_loss(l):
            return jnp.mean(ops.softmax_cross_entropy(l, labels))

        np.testing.assert_allclose(
            np.asarray(g_tp), np.asarray(jax.grad(full_loss)(logits)),
            rtol=1e-5, atol=1e-6)


class TestParallelState:
    def test_world_sizes(self, tp_mesh):
        assert parallel_state.get_tensor_model_parallel_world_size() == 4
        assert parallel_state.get_data_parallel_world_size() == 2
        assert parallel_state.get_pipeline_model_parallel_world_size() == 1
        assert parallel_state.model_parallel_is_initialized()

    def test_initialize_signature_parity(self):
        m = parallel_state.initialize_model_parallel(2, 2)
        assert m.shape["tensor"] == 2 and m.shape["pipe"] == 2
        parallel_state.destroy_model_parallel()
        assert not parallel_state.model_parallel_is_initialized()

    def test_ranks_inside_shard_map(self, tp_mesh):
        f = shard_map(
            lambda: parallel_state.get_tensor_model_parallel_rank()[None],
            mesh=tp_mesh, in_specs=(), out_specs=P("tensor"))
        ranks = np.asarray(f())
        assert sorted(ranks.tolist()) == [0, 1, 2, 3]
