"""Aux subsystems: checkpoint save/resume (incl. loss-scale state),
debug tripwires, metrics writer (SURVEY.md §5)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from apex_tpu import amp, utils


class TestCheckpoint:
    def test_train_state_roundtrip(self, tmp_path, rng):
        params = {"w": jnp.asarray(rng.normal(size=(4, 2)), jnp.float32)}
        state = amp.initialize(lambda p, x: x @ p["w"], params,
                               optax.adam(1e-3), opt_level="O2",
                               half_dtype=jnp.float16)
        # advance so step/scale/opt state are non-trivial
        x = jnp.ones((3, 4))
        grads = jax.grad(lambda p: jnp.sum(
            state.apply_fn(p, x)) * 2.0)(state.compute_params())
        state, _ = state.apply_gradients(grads=grads)

        saveable = {"params": state.params,
                    "opt_state": state.opt_state,
                    "step": state.step,
                    "amp": state.amp_state_dict()}
        path = str(tmp_path / "ckpt")
        utils.save_checkpoint(path, saveable)
        restored = utils.restore_checkpoint(path, saveable)
        np.testing.assert_array_equal(np.asarray(restored["params"]["w"]),
                                      np.asarray(state.params["w"]))
        assert int(restored["step"]) == 1
        assert float(restored["amp"]["loss_scale"]) == float(
            state.loss_scale_state.loss_scale)
        state2 = state.load_amp_state_dict(restored["amp"])
        assert float(state2.loss_scale_state.loss_scale) == float(
            state.loss_scale_state.loss_scale)

    def test_sharded_roundtrip_resharding_mesh(self, tmp_path, rng):
        """TP=2 x DP=2 sharded save → restore into a *differently*
        sharded target — bit-exact params + loss-scale resume (round-1
        verdict item 8; reference analogue: DistributedFusedAdam's
        sharded-state gather/scatter, SURVEY.md §5 checkpoint row)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from apex_tpu.core import mesh as mesh_lib
        from apex_tpu.optim import fused_adam

        mesh = mesh_lib.initialize_mesh(data_parallel_size=-1,
                                        tensor_model_parallel_size=2)
        try:
            col = NamedSharding(mesh, P("tensor", None))
            row = NamedSharding(mesh, P(None, "tensor"))
            rep = NamedSharding(mesh, P())
            params = {
                "w": jax.device_put(
                    jnp.asarray(rng.normal(size=(8, 8)), jnp.float32), col),
                "b": jax.device_put(jnp.zeros((8,), jnp.float32), rep),
            }
            state = amp.initialize(
                lambda p, x: x @ p["w"] + p["b"], params,
                fused_adam(1e-3), opt_level="O2",
                half_dtype=jnp.float16)
            x = jnp.ones((3, 8))
            grads = jax.grad(lambda p: jnp.sum(
                state.apply_fn(p, x)) * 2.0)(state.compute_params())
            state, _ = state.apply_gradients(grads=grads)

            saveable = {"params": state.params,
                        "opt_state": state.opt_state,
                        "step": state.step,
                        "amp": state.amp_state_dict()}
            path = str(tmp_path / "sharded_ckpt")
            utils.save_checkpoint(path, saveable)

            # target with transposed sharding for w: restore must land
            # on the new placement, values unchanged
            target = jax.tree.map(lambda a: a, saveable)
            target["params"] = dict(target["params"])
            target["params"]["w"] = jax.device_put(
                jnp.zeros_like(state.params["w"]), row)
            restored = utils.restore_checkpoint(path, target)

            got_w = restored["params"]["w"]
            assert got_w.sharding.is_equivalent_to(row, got_w.ndim)
            np.testing.assert_array_equal(np.asarray(got_w),
                                          np.asarray(state.params["w"]))
            np.testing.assert_array_equal(
                np.asarray(jax.tree.leaves(restored["opt_state"])[0]),
                np.asarray(jax.tree.leaves(state.opt_state)[0]))
            state2 = state.load_amp_state_dict(restored["amp"])
            assert float(state2.loss_scale_state.loss_scale) == float(
                state.loss_scale_state.loss_scale)
            assert int(restored["step"]) == 1
        finally:
            mesh_lib.destroy_mesh()

    def test_save_refuses_overwrite_by_default(self, tmp_path):
        """Regression: force used to default True, silently clobbering
        an existing checkpoint."""
        tree = {"a": jnp.arange(3.0)}
        path = str(tmp_path / "ckpt")
        utils.save_checkpoint(path, tree)
        with pytest.raises(FileExistsError, match="force=True"):
            utils.save_checkpoint(path, {"a": jnp.zeros(3)})
        # the refused save must not have touched the original
        restored = utils.restore_checkpoint(path, tree)
        np.testing.assert_array_equal(np.asarray(restored["a"]),
                                      np.arange(3.0))
        # explicit force overwrites
        utils.save_checkpoint(path, {"a": jnp.zeros(3)}, force=True)
        restored = utils.restore_checkpoint(path, tree)
        np.testing.assert_array_equal(np.asarray(restored["a"]),
                                      np.zeros(3))

    def test_manager_rolls(self, tmp_path):
        import orbax.checkpoint as ocp
        mngr = utils.checkpoint_manager(str(tmp_path / "m"),
                                        max_to_keep=2)
        tree = {"a": jnp.zeros((2,))}
        for step in range(4):
            mngr.save(step, args=ocp.args.StandardSave(tree))
        mngr.wait_until_finished()
        assert mngr.latest_step() == 3
        assert len(mngr.all_steps()) <= 2


class TestDebug:
    def test_checkify_finite_raises(self):
        from jax.experimental import checkify

        def f(x):
            return utils.checkify_finite({"x": x}, "x")["x"] * 2

        checked = checkify.checkify(jax.jit(f))
        err, out = checked(jnp.ones((3,)))
        err.throw()  # no error
        err, out = checked(jnp.array([1.0, jnp.inf, 0.0]))
        with pytest.raises(Exception, match="non-finite"):
            err.throw()

    def test_tree_health(self):
        rep = utils.tree_health(
            {"a": jnp.array([1.0, jnp.nan]), "b": jnp.array([jnp.inf]),
             "i": jnp.array([1, 2])})
        assert rep["a"]["nan"] == 1
        assert rep["b"]["inf"] == 1
        assert "i" not in rep

    def test_nan_check_mode_scoped(self):
        assert not jax.config.jax_debug_nans
        with utils.nan_check_mode():
            assert jax.config.jax_debug_nans
        assert not jax.config.jax_debug_nans


class TestMetrics:
    def test_writer_from_jit(self):
        rows = []
        w = utils.MetricsWriter(sink=lambda s, m: rows.append((s, m)))

        @jax.jit
        def step(i, x):
            loss = jnp.sum(x) * i
            utils.log_metrics(w, i, {"loss": loss})
            return loss

        for i in range(3):
            step(i, jnp.ones((2,))).block_until_ready()
        jax.effects_barrier()
        w.drain()
        assert [s for s, _ in rows] == [0, 1, 2]
        assert rows[2][1]["loss"] == 4.0

    def test_out_of_order_delivery_ordered_on_drain(self):
        """JAX guarantees no callback delivery order — emissions tagged
        with their device-side step must come out of drain() step-
        ascending, duplicates dropped."""
        rows = []
        w = utils.MetricsWriter(sink=lambda s, m: rows.append((s, m)))
        w(3, {"loss": 3.0})
        w(1, {"loss": 1.0})
        w(3, {"loss": 99.0, "extra": 7.0})   # same step: first wins
        w(2, {"loss": 2.0})                  # per key, new keys merge
        drained = w.drain()
        assert [s for s, _ in rows] == [1, 2, 3]
        assert rows[2][1]["loss"] == 3.0
        assert rows[2][1]["extra"] == 7.0
        assert drained == rows
        # duplicates are dropped across drains too, and a late older
        # step still lands sorted in history
        w(3, {"loss": 77.0})
        w(0, {"loss": 0.0})
        w.drain()
        assert [s for s, _ in rows] == [1, 2, 3, 0]
        assert [s for s, _ in w.history] == [0, 1, 2, 3]

    def test_history_sorted_without_drain_sink(self):
        w = utils.MetricsWriter(sink=lambda s, m: None)
        for s in (5, 2, 9, 2):
            w(s, {"v": float(s)})
        w.drain()
        assert [s for s, _ in w.history] == [2, 5, 9]

    def test_merge_namespaces_colliding_steps(self):
        """Two replicas with identical step counters aggregate into
        one fleet view: keys are namespaced per source, the source's
        own step rides along as ``<name>/step``, and the per-step
        first-wins dedupe never clobbers across sources."""
        a, b = utils.MetricsWriter(), utils.MetricsWriter()
        a(0, {"tps": 1.0})
        a(32, {"tps": 2.0})
        b(0, {"tps": 10.0})          # same step tags as a — on purpose
        b(32, {"tps": 20.0})
        rows = []
        fleet = utils.MetricsWriter(sink=lambda s, m: rows.append((s, m)))
        staged = fleet.merge({"r0": a, "r1": b})
        assert len(staged) == 4
        fleet.drain()
        assert len(rows) == 4        # nothing deduped away
        # per source: ascending source step, order preserved
        r0 = [m for _, m in rows if "r0/tps" in m]
        assert [m["r0/step"] for m in r0] == [0.0, 32.0]
        assert [m["r0/tps"] for m in r0] == [1.0, 2.0]
        r1 = [m for _, m in rows if "r1/tps" in m]
        assert [m["r1/tps"] for m in r1] == [10.0, 20.0]
        # the fleet axis itself is strictly ascending (drain order)
        steps = [s for s, _ in rows]
        assert steps == sorted(steps) and len(set(steps)) == 4

    def test_merge_dedupes_across_repeated_merges(self):
        a = utils.MetricsWriter()
        a(1, {"x": 1.0})
        fleet = utils.MetricsWriter(sink=lambda s, m: None)
        assert len(fleet.merge({"a": a})) == 1
        # a's row already drained into the fleet — a second merge (and
        # a replayed emission of the same source step) stage nothing
        assert fleet.merge({"a": a}) == []
        a(1, {"x": 99.0})
        assert fleet.merge({"a": a}) == []
        # but a NEW source step flows through
        a(2, {"x": 2.0})
        assert len(fleet.merge({"a": a})) == 1

    def test_merge_interleaves_with_direct_rows_via_advance_step(self):
        """Aggregate summary rows tagged with advance_step() land
        after the rows already merged — arrival order, no collisions
        with any source's step axis."""
        src = utils.MetricsWriter()
        src(7, {"v": 1.0})
        rows = []
        fleet = utils.MetricsWriter(sink=lambda s, m: rows.append((s, m)))
        fleet(0, {"fleet/tick": 0.0})          # a direct early row
        fleet.merge({"r": src})
        fleet(fleet.advance_step(), {"fleet/tick": 1.0})
        fleet.drain()
        assert [sorted(m) for _, m in rows] == [
            ["fleet/tick"], ["r/step", "r/v"], ["fleet/tick"]]
        steps = [s for s, _ in rows]
        assert steps == sorted(steps) and len(set(steps)) == 3

    def test_namespaced_sink_pushes_into_target(self):
        """The push twin: a writer that drains itself (the replica
        server pattern) forwards its rows into the fleet writer."""
        rows = []
        fleet = utils.MetricsWriter(sink=lambda s, m: rows.append((s, m)))
        child = utils.MetricsWriter(
            sink=utils.namespaced_sink("replica3", fleet))
        child(5, {"tps": 2.5})
        child.drain()                # the server-side self-drain
        fleet.drain()
        assert rows == [(0, {"replica3/tps": 2.5, "replica3/step": 5.0})]

    def test_merge_concurrent_with_emitters_exactly_once(self):
        """ISSUE-9 concurrency audit of merge()/namespaced_sink: the
        aggregator pulls while source writers' emitter threads keep
        staging — the discipline the ``guarded-by`` annotations on
        ``_pending``/``_seen``/``_axis``/``history`` declare.  Every
        (source, step) lands exactly once, nothing is lost or
        duplicated, and the combined history stays step-sorted."""
        import threading

        n_steps = 150
        agg = utils.MetricsWriter(sink=lambda s, m: None)
        sources = {f"r{i}": utils.MetricsWriter() for i in range(3)}

        def emit(w):
            for s in range(n_steps):
                w(s, {"v": float(s)})

        threads = [threading.Thread(target=emit, args=(w,))
                   for w in sources.values()]
        for t in threads:
            t.start()
        merged = []
        while any(t.is_alive() for t in threads):
            merged += agg.merge(sources)    # pull mid-emission
        for t in threads:
            t.join()
        merged += agg.merge(sources)        # sweep the tail
        per_source_steps = {}
        for _, row in merged:
            name = next(iter(row)).split("/")[0]
            per_source_steps.setdefault(name, []).append(
                row[f"{name}/step"])
        assert set(per_source_steps) == set(sources)
        for name, steps in per_source_steps.items():
            # exactly once each, and per-source order preserved
            assert steps == sorted(steps) == [float(s)
                                              for s in range(n_steps)]
        agg.drain()
        hist = [s for s, _ in agg.history]
        assert hist == sorted(hist) and len(hist) == 3 * n_steps


class TestProfiler:
    """jax.profiler wrappers (SURVEY.md §5 tracing row — exceeds the
    reference, which has no first-class profiling)."""

    # [slow: ~14s of trace-collection I/O for a capability proof — the
    # tier-1 wall budget rides its edge; runs under -m slow + on-chip]
    @pytest.mark.slow
    def test_trace_writes_artifacts(self, tmp_path):
        import jax

        d = str(tmp_path / "trace")
        with utils.profiler.trace(d):
            with utils.profiler.annotate("probe_matmul"):
                x = jnp.ones((64, 64)) @ jnp.ones((64, 64))
                jax.block_until_ready(x)
        import pathlib
        files = list(pathlib.Path(d).rglob("*"))
        assert any(f.is_file() for f in files), files

    def test_memory_profile_written(self, tmp_path):
        p = str(tmp_path / "mem.prof")
        _ = jnp.ones((128, 128)) + 1.0
        utils.profiler.save_device_memory_profile(p)
        import os
        assert os.path.exists(p) and os.path.getsize(p) > 0
