"""Chaos tier (``pytest -m chaos``): end-to-end fault trajectories.

Two acceptance soaks for the resilience layer (docs/resilience.md):

- **kill-and-resume**: a training run killed by an injected preemption
  auto-resumes from the latest valid checkpoint and reproduces the
  uninterrupted loss trajectory (the ``test_loss_trajectory.py``
  claim, extended across a process "death"); a corrupted latest
  checkpoint is detected by its manifest hashes and the run falls back
  to the previous one — trajectory still intact.
- **serving soak**: with transient step faults firing throughout and
  per-request deadlines in the mix, every accepted request either
  completes or fails with an explicit terminal error — none lost, none
  hung — the server keeps serving, and the engine's compile/retrace
  budgets are exactly the warmup budgets (recovery replays compiled
  programs, it never traces new ones).
- **fleet soak** (ISSUE 6): SIGKILL-equivalent replica death — and a
  graceful drain — under mixed greedy/top-p/deadline traffic on a
  3-replica ``FleetRouter``: zero lost/hung requests, migrated greedy
  streams token-identical to an uninterrupted ``generate()``,
  survivors' paged pools back to ``blocks_in_use == 0``, and every
  replica's trace budget still exactly 4 executables × 1 trace.
- **quantized paged soak** (ISSUE 8): the sharing+spec paged soak
  with ``kv_dtype="int8"`` — zero lost/hung, ``blocks_in_use == 0``
  (per-page scales freed with their pages), budgets exactly 5 × 1.
- **sharded-replica kill soak** (ISSUE 13): the fleet soak with a
  TENSOR-PARALLEL replica in the pool (one replica spanning 2 chips,
  KV pool sharded on kv_heads) — the TP replica is the one killed
  under mixed traffic: zero lost/hung, its tenants migrate onto
  single-chip survivors token-identically (migration re-prefills from
  the streamed prefix, so replicas of DIFFERENT mesh shapes
  interoperate), survivors' pools drain to ``blocks_in_use == 0``.
- **ZeRO-sharded kill-and-resume** (ISSUE 11): the training soak with
  optimizer state ZeRO-2-sharded over the 8-device mesh — checkpoint
  mid-run, kill, restore onto the ``zero_shardings`` placement,
  spliced trajectory allclose to uninterrupted; plus the
  ``bert_o1_zero`` bench leg's CPU-tiny smoke (measured hbm drop,
  grown-batch row, loss agreement).

The serving and fleet soaks also run under the **strict runtime lock
sanitizer** (``apex_tpu.utils.lockcheck``, ISSUE 9): every lock in the
stack is wrapped with an acquisition-order recorder and every
``# graftlint: guarded-by`` field access is verified to hold its
declared lock — the soak asserts zero reports at the end.  The
chaos-smoke CI job exports ``APEX_TPU_LOCKCHECK=strict`` to document
the mode; the soaks force ``strict=True`` regardless.

The training soaks additionally run under the **strict runtime
numerics sanitizer** (``apex_tpu.utils.numcheck``, ISSUE 10 — the
precision pass's dynamic twin, same mold): the amp cast boundaries,
loss-scale path and optimizer step are hooked, grad underflow /
non-finite stats recorded, and the soak asserts zero numerics
violations at the end.  ``TestMixedPrecisionBenchSmoke`` is the bench
leg's chaos twin: the BERT-bench O2 recipe at toy size, with a planted
overflow step proving skip/backoff fires (and is *counted*) without a
violation.  The chaos-smoke CI job exports ``APEX_TPU_NUMCHECK=strict``
to document the mode; the soaks force ``strict=True`` regardless.

CI runs these in the dedicated ``chaos-smoke`` job (small configs,
CPU).  They carry ``slow`` too: the tier-1 ``-m 'not slow'`` gate
already rides its wall-clock budget, and these three dots cost ~a
minute of mini-training — the chaos job (``-m chaos``) is their gate;
the fast unit tier in ``tests/test_resilience.py`` stays in tier-1.
"""

import os
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu import amp
from apex_tpu.models import GPTConfig, GPTModel, generate, gpt_loss_fn
from apex_tpu.optim import fused_adam
from apex_tpu.resilience import (
    FaultPlan,
    FaultSpec,
    ResilientCheckpointer,
    ResilientLoop,
    active,
)
from apex_tpu.serving import (
    FleetRouter,
    InferenceServer,
    RequestFailed,
    tp_mesh,
)
from apex_tpu.transformer.testing import standalone_gpt
from apex_tpu.utils import (MetricsWriter, lockcheck, numcheck,
                            shardcheck, tracecheck)

pytestmark = [pytest.mark.chaos, pytest.mark.slow]


class TestKillAndResumeTrajectory:
    STEPS = 40
    B, S = 4, 16
    CKPT_EVERY = 8

    @pytest.fixture(autouse=True)
    def _numcheck_strict(self):
        # ISSUE-10: the GPT soak runs under the strict runtime
        # numerics sanitizer — installed before the first jit trace so
        # the hooks ride the compiled step; torn down even on failure
        # so the process-wide wrappers never leak into other tests
        numcheck.reset()
        numcheck.instrument(strict=True)
        yield
        numcheck.uninstrument()
        numcheck.reset()

    def _make(self):
        model, init_params = standalone_gpt(seed=0, max_seq_len=self.S)
        vocab = model.cfg.vocab_size
        # the trajectory-test recipe: a fixed pool of batches, cycled,
        # so the signal is memorization speed and data is a pure
        # function of the step index (what makes resume exact)
        ids = jax.random.randint(
            jax.random.PRNGKey(1234), (4, self.B, self.S + 1), 0,
            vocab, jnp.int32)

        def make_state():
            return amp.initialize(
                model.apply, {"params": init_params},
                fused_adam(3e-4), opt_level="O0")

        @jax.jit
        def step(state, chunk):
            inputs, labels = chunk[:, :-1], chunk[:, 1:]

            def loss_fn(p):
                logits = state.apply_fn(p, inputs)
                return gpt_loss_fn(logits.astype(jnp.float32), labels)

            loss, grads = jax.value_and_grad(loss_fn)(state.params)
            new_state, _finite = state.apply_gradients(grads=grads)
            return new_state, loss

        def loop_step(state, batch):
            state, loss = step(state, batch)
            return state, {"loss": loss}

        def data_fn(i):
            return ids[i % 4]

        return make_state, step, loop_step, data_fn

    def _rows(self, writer):
        return {s: r["loss"] for s, r in writer.history}

    def test_preempt_resume_and_corrupt_skip(self, tmp_path):
        make_state, step, loop_step, data_fn = self._make()

        # ------------------------- the uninterrupted reference run
        state = make_state()
        ref = []
        for i in range(self.STEPS):
            state, loss = step(state, data_fn(i))
            ref.append(float(loss))
        assert np.all(np.isfinite(ref))
        assert ref[-1] < ref[0]             # it actually trains

        # ------------------------- run 1: killed by injected preemption
        ckpt_dir = str(tmp_path / "ckpts")
        kill_at = 17
        writer1 = MetricsWriter(sink=lambda s, m: None)
        loop1 = ResilientLoop(
            loop_step,
            checkpointer=ResilientCheckpointer(ckpt_dir, keep=3),
            checkpoint_every=self.CKPT_EVERY,
            scalars_of=lambda aux: {"loss": aux["loss"]},
            metrics=writer1)
        plan = FaultPlan([FaultSpec(site="train.step", kind="preempt",
                                    step=kill_at, times=1)])
        with active(plan):
            _carry, report1 = loop1.run(make_state(), data_fn,
                                        self.STEPS)
        assert report1.preempted
        assert report1.final_step == kill_at

        # corrupt the preemption checkpoint: flip bytes in one payload
        # file of the newest step dir — restore must detect it via the
        # manifest hashes and fall back to the previous checkpoint
        ck = ResilientCheckpointer(ckpt_dir, keep=3)
        assert ck.latest_step() == kill_at
        newest = os.path.join(ckpt_dir, f"step_{kill_at:08d}")
        victims = []
        for base, _dirs, names in os.walk(newest):
            victims.extend(
                os.path.join(base, n) for n in names
                if "manifest" not in n
                and os.path.getsize(os.path.join(base, n)) > 0)
        with open(sorted(victims)[0], "r+b") as f:
            blob = f.read(16)
            f.seek(0)
            f.write(bytes(b ^ 0xFF for b in blob))

        # ------------------------- run 2: auto-resume, finish the run
        writer2 = MetricsWriter(sink=lambda s, m: None)
        loop2 = ResilientLoop(
            loop_step,
            checkpointer=ResilientCheckpointer(ckpt_dir, keep=3),
            checkpoint_every=self.CKPT_EVERY,
            scalars_of=lambda aux: {"loss": aux["loss"]},
            metrics=writer2)
        carry2, report2 = loop2.run(make_state(), data_fn, self.STEPS)
        # the corrupt step-17 checkpoint was skipped for step 16
        assert report2.resumed_from == 16
        assert report2.final_step == self.STEPS
        assert not report2.preempted

        # ------------------------- the spliced trajectory matches
        rows1, rows2 = self._rows(writer1), self._rows(writer2)
        # metrics are emitted at step = cursor+1 (1-based)
        spliced = [rows1[i] if i <= report2.resumed_from else rows2[i]
                   for i in range(1, self.STEPS + 1)]
        np.testing.assert_allclose(
            spliced, ref, rtol=0, atol=1e-5,
            err_msg="resumed trajectory diverged from uninterrupted")
        # and the replayed overlap (steps 17 after rewind vs run 1's
        # own pre-kill steps) is bit-identical too: same data, same
        # restored state, same program
        overlap = [i for i in rows2 if i in rows1]
        for i in overlap:
            np.testing.assert_allclose(rows2[i], rows1[i], rtol=0,
                                       atol=1e-5)

        # ------------------- zero numerics violations across the soak
        # (kill, corrupt-checkpoint fallback and resume included) —
        # and the sanitizer demonstrably observed the optimizer steps
        jax.effects_barrier()
        numcheck.assert_clean()
        assert numcheck.summary()["grad_stat_steps"] > 0


class TestZeroKillAndResumeTrajectory:
    """ISSUE-11 chaos arm: the kill-and-resume soak with the optimizer
    state ZeRO-2-SHARDED over an 8-device mesh.  Checkpoint mid-run,
    kill via an injected preemption, restore with the
    ``zero_shardings`` placement (the checkpoint target is the placed
    state, so orbax lands the master/moment shards back on their mesh
    rows), and the spliced trajectory must match the uninterrupted run
    — sharding the state must not change WHAT is persisted, only
    where it lives.  Runs under the strict numerics sanitizer: fp32
    master shards verified at runtime across kill and resume.
    """

    STEPS = 40
    B, S = 8, 16            # batch divisible by the 8-way mesh
    CKPT_EVERY = 8

    @pytest.fixture(autouse=True)
    def _sanitizers_strict(self):
        # ISSUE-16: the placement sanitizer rides alongside the
        # numerics one — the declared ZeRO layout is re-checked
        # against every compiled step's actual output shardings
        numcheck.reset()
        numcheck.instrument(strict=True)
        shardcheck.reset()
        yield
        shardcheck.uninstrument()
        shardcheck.reset()
        numcheck.uninstrument()
        numcheck.reset()

    def _make(self):
        from jax.sharding import PartitionSpec as P

        from apex_tpu.parallel import (ZeroConfig, zero_shardings,
                                       zero_state_specs)

        model, init_params = standalone_gpt(seed=0, max_seq_len=self.S)
        vocab = model.cfg.vocab_size
        ids = jax.random.randint(
            jax.random.PRNGKey(1234), (4, self.B, self.S + 1), 0,
            vocab, jnp.int32)
        # raw mesh, fully-manual step (test_loss_trajectory precedent)
        mesh = jax.sharding.Mesh(np.array(jax.devices()[:8]),
                                 ("data",))
        tx = fused_adam(3e-4)   # ONE transform: shared static treedef

        def make_state():
            state = amp.initialize(
                model.apply, {"params": init_params}, tx,
                opt_level="O0",
                zero=ZeroConfig(axis="data", stage=2, axis_size=8))
            # committed sharded placement — doubles as the
            # checkpoint-restore target
            return jax.device_put(state,
                                  zero_shardings(state, mesh=mesh))

        specs = zero_state_specs(make_state())

        def z_step(state, chunk):
            inputs, labels = chunk[:, :-1], chunk[:, 1:]

            def loss_fn(p):
                logits = state.apply_fn(p, inputs)
                return gpt_loss_fn(logits.astype(jnp.float32), labels)

            loss, grads = jax.value_and_grad(loss_fn)(state.params)
            new_state, _finite = state.apply_gradients(grads=grads)
            return new_state, jax.lax.pmean(loss, "data")

        step = jax.jit(jax.shard_map(
            z_step, mesh=mesh,
            in_specs=(specs, P("data")), out_specs=(specs, P()),
            check_vma=False))

        # runtime placement oracle (ISSUE-16): the step's declared
        # ZeRO layout — master/moment shards on their mesh rows,
        # params replicated, pmean'd loss replicated — verified
        # against the compiled executable's actual outputs every call
        declared = (jax.tree.map(
            lambda s: jax.sharding.NamedSharding(mesh, s), specs,
            is_leaf=lambda x: isinstance(x, P)),
            jax.sharding.NamedSharding(mesh, P()))
        step = shardcheck.wrap_step(step, declared=declared,
                                    mesh=mesh, name="zero.train_step",
                                    strict=True)

        def loop_step(state, batch):
            state, loss = step(state, batch)
            return state, {"loss": loss}

        def data_fn(i):
            return ids[i % 4]

        return make_state, step, loop_step, data_fn

    def _rows(self, writer):
        return {s: r["loss"] for s, r in writer.history}

    def test_sharded_preempt_resume_matches_uninterrupted(
            self, tmp_path):
        from jax.sharding import PartitionSpec as P

        make_state, step, loop_step, data_fn = self._make()

        # ------------------------- the uninterrupted reference run
        state = make_state()
        ref = []
        for i in range(self.STEPS):
            state, loss = step(state, data_fn(i))
            ref.append(float(loss))
        assert np.all(np.isfinite(ref))
        assert ref[-1] < ref[0]

        # ------------------- run 1: killed by injected preemption
        ckpt_dir = str(tmp_path / "ckpts")
        kill_at = 17
        writer1 = MetricsWriter(sink=lambda s, m: None)
        loop1 = ResilientLoop(
            loop_step,
            checkpointer=ResilientCheckpointer(ckpt_dir, keep=3),
            checkpoint_every=self.CKPT_EVERY,
            scalars_of=lambda aux: {"loss": aux["loss"]},
            metrics=writer1)
        plan = FaultPlan([FaultSpec(site="train.step", kind="preempt",
                                    step=kill_at, times=1)])
        with active(plan):
            _carry, report1 = loop1.run(make_state(), data_fn,
                                        self.STEPS)
        assert report1.preempted
        assert report1.final_step == kill_at

        # ------------------- run 2: auto-resume onto the SHARDED
        # placement (the target is the zero_shardings-placed state)
        writer2 = MetricsWriter(sink=lambda s, m: None)
        loop2 = ResilientLoop(
            loop_step,
            checkpointer=ResilientCheckpointer(ckpt_dir, keep=3),
            checkpoint_every=self.CKPT_EVERY,
            scalars_of=lambda aux: {"loss": aux["loss"]},
            metrics=writer2)
        carry2, report2 = loop2.run(make_state(), data_fn, self.STEPS)
        assert report2.resumed_from == kill_at
        assert report2.final_step == self.STEPS
        assert not report2.preempted

        # master shards came back ON their mesh rows: 1/8-sized
        # addressable shards with the zero spec
        for leaf in jax.tree.leaves(carry2.opt_state.master):
            # (trailing-None spec normalization differs across paths)
            assert tuple(leaf.sharding.spec)[:1] == ("data",)
            assert leaf.sharding.shard_shape(leaf.shape)[0] * 8 \
                == leaf.shape[0]
            assert leaf.dtype == jnp.float32

        # ------------------------- the spliced trajectory matches
        rows1, rows2 = self._rows(writer1), self._rows(writer2)
        spliced = [rows1[i] if i <= report2.resumed_from else rows2[i]
                   for i in range(1, self.STEPS + 1)]
        np.testing.assert_allclose(
            spliced, ref, rtol=0, atol=1e-5,
            err_msg="ZeRO-sharded resume diverged from uninterrupted")

        # ------------------- strict numerics oracle: clean, and the
        # shard-local updates consumed only fp32 masters
        jax.effects_barrier()
        numcheck.assert_clean()
        hist = numcheck.site_histograms()
        assert set(hist["apply_gradients.master_shards"]) \
            == {"float32"}
        # ... and the placement oracle: every step of all three runs
        # actually landed the shards where the ZeRO spec declares
        shardcheck.assert_clean()
        zsite = shardcheck.site_shardings()["zero.train_step"]
        assert zsite["checked"] > 0
        assert zsite["mismatched"] == 0


class TestZeroBenchSmoke:
    """ISSUE-11 CI bench smoke: the ``bert_o1_zero`` leg at a CPU-tiny
    preset — the emission must carry a measured hbm_peak/state-bytes
    drop for ZeRO-2 vs the replicated-DP baseline, a grown-batch row
    that fits the DP HBM budget, and final-loss agreement at equal
    batch.  (The full-size leg rides ``bench_configs.py bert_o1``
    on-chip; this pins the protocol and the emission schema.)"""

    def test_zero_leg_emits_hbm_drop(self):
        import json
        import subprocess
        import sys

        repo = os.path.dirname(os.path.dirname(os.path.abspath(
            __file__)))
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                            + " --xla_force_host_platform_device"
                              "_count=8").strip()
        env.update({"BENCH_BERT_ZERO_LAYERS": "1", "BENCH_BATCH": "8",
                    "BENCH_SEQ": "32", "BENCH_ZERO_STEPS": "2"})
        r = subprocess.run(
            [sys.executable,
             os.path.join(repo, "bench_configs.py"), "bert_o1_zero"],
            env=env, capture_output=True, text=True, timeout=900)
        assert r.returncode == 0, r.stderr[-2000:]
        rows = [json.loads(l) for l in r.stdout.splitlines()
                if l.startswith("{")]
        assert rows, r.stdout[-2000:]
        out = rows[-1]
        assert out["metric"] == "bert_o2_zero2_samples_per_sec"
        # the tentpole acceptance: measured hbm drop, sharded-state
        # residency drop, grown batch inside the DP budget, loss
        # agreement at equal batch
        assert out["hbm_peak_drop_bytes"] > 0, out
        assert out["state_bytes_saved_per_chip"] > 0, out
        assert out["rows"]["zero2"]["state_bytes_per_chip"] \
            < out["rows"]["dp"]["state_bytes_per_chip"]
        assert out["grown_batch"] >= out["rows"]["dp"]["global_batch"]
        assert out["grown_batch_fits_dp_hbm_budget"], out
        assert out["final_loss_delta_equal_batch"] < 0.05, out
        model = out["zero_bytes_on_wire"]
        assert model["state_bytes_saved_per_chip"] > 0
        assert model["wire_reduction_vs_dp"] > 1.0


class TestMixedPrecisionBenchSmoke:
    """ISSUE-10 bench-smoke twin: the bench BERT leg's mixed-precision
    recipe (O2 + FusedAdam + ``scale_loss`` + ``apply_gradients``) at
    toy size, under the strict runtime numerics sanitizer — with a
    deliberately planted fp16 overflow step proving that the dynamic
    loss scaler's skip/backoff path fires, is *counted* on the shared
    ``amp.loss_scale.*`` counters (the bench emission's source), and is
    NOT a numerics violation; the trajectory keeps training through it.
    """

    STEPS = 18
    B, S = 4, 16

    def test_o2_fp16_smoke_strict_numcheck_clean(self):
        from apex_tpu.core.loss_scale import DynamicLossScale
        from apex_tpu.transformer.testing import standalone_gpt
        from apex_tpu.utils.metrics import counters

        numcheck.reset()
        numcheck.instrument(strict=True)
        try:
            model, init_params = standalone_gpt(seed=0, max_seq_len=self.S)
            vocab = model.cfg.vocab_size
            ids = jax.random.randint(
                jax.random.PRNGKey(7), (4, self.B, self.S + 1), 0,
                vocab, jnp.int32)

            state = amp.initialize(
                model.apply, {"params": init_params}, fused_adam(3e-4),
                opt_level="O2", half_dtype=jnp.float16)
            # short growth interval so the soak exercises growth too
            ls = DynamicLossScale(growth_interval=4)
            state = state.replace(loss_scaler=ls,
                                  loss_scale_state=ls.init())

            @jax.jit
            def step(state, chunk, boost):
                inputs, labels = chunk[:, :-1], chunk[:, 1:]

                def loss_fn(p):
                    logits = state.apply_fn(p, inputs)
                    loss = gpt_loss_fn(logits.astype(jnp.float32),
                                       labels)
                    # `boost` plants a deterministic overflow: at the
                    # poisoned step the scaled loss (and so the fp16
                    # grads) goes inf, driving the skip/backoff path
                    return state.scale_loss(loss * boost), loss

                grads, loss = jax.grad(loss_fn, has_aux=True)(
                    state.compute_params())
                new_state, finite = state.apply_gradients(grads=grads)
                return new_state, loss, finite

            g0 = counters.get("amp.loss_scale.growth")
            b0 = counters.get("amp.loss_scale.backoff")
            overflow_at = 9
            losses, finites = [], []
            for i in range(self.STEPS):
                boost = jnp.asarray(
                    1e30 if i == overflow_at else 1.0, jnp.float32)
                state, loss, finite = step(state, ids[i % 4], boost)
                losses.append(float(loss))
                finites.append(bool(finite))
            jax.effects_barrier()

            # the planted overflow skipped exactly its own step...
            assert not finites[overflow_at]
            assert all(f for i, f in enumerate(finites)
                       if i != overflow_at)
            # ...was counted as a backoff (and clean runs as growth)
            assert counters.get("amp.loss_scale.backoff") == b0 + 1
            assert counters.get("amp.loss_scale.growth") > g0
            # the un-boosted losses stayed finite and it still trains
            assert np.all(np.isfinite(losses))
            assert losses[-1] < losses[0]

            # strict sanitizer: the overflow is diet, not a violation;
            # masters stayed fp32 through every step
            numcheck.assert_clean()
            s = numcheck.summary()
            assert s["grad_stat_steps"] == self.STEPS
            assert s["nonfinite_grad_steps"] == 1
            assert s["sites"]["apply_gradients.params"] \
                == {"float32": s["sites"]["apply_gradients.params"]
                    .get("float32", 0)}   # fp32 masters only
            assert "float16" in s["sites"]["apply_gradients.grads"]
        finally:
            numcheck.uninstrument()
            numcheck.reset()


class TestServingChaosSoak:
    @pytest.fixture(autouse=True)
    def _shardcheck(self):
        # ISSUE-16: the soak runs under the strict placement
        # sanitizer; torn down even on failure so the process-wide
        # step wrappers and monitoring listener never leak
        shardcheck.reset()
        yield
        shardcheck.uninstrument()
        shardcheck.reset()

    def _tiny(self):
        cfg = GPTConfig.tiny(position_embedding="learned",
                             scan_layers=True)
        model = GPTModel(cfg)
        params = model.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, 4), jnp.int32))
        return model, {"params": params["params"]}

    def test_soak_no_lost_requests_no_retraces(self):
        model, params = self._tiny()
        server = InferenceServer(model, params, max_slots=3,
                                 prompt_buckets=(4, 8, 16))
        # runtime lock sanitizer, strict: order-inversion recording on
        # every lock in the stack plus guarded-by field verification
        # (docs/graftlint.md) — instrumented before the worker starts
        lockcheck.reset()
        lockcheck.instrument(server, strict=True)
        # ... and the strict placement sanitizer on the same server:
        # single-chip, so no declared layout to verify, but every step
        # window must stay free of unexpected device-to-host traffic
        shardcheck.instrument(server, strict=True)
        # transient faults throughout the soak (attempt counter: every
        # 5th decode attempt), plus one admission-path fault
        plan = FaultPlan([
            FaultSpec(site="serving.step", kind="transient", every=5,
                      times=4),
            FaultSpec(site="serving.admit", kind="transient", step=3,
                      times=1),
        ])
        rng = np.random.default_rng(23)
        # budgets small enough that continuation prompts (prompt ++
        # emitted tokens) always re-bucket: L + n <= 16
        cases = [
            (3, 4, 0.0, None, None), (7, 3, 0.8, 20, None),
            (5, 5, 1.2, 5, 0.9), (2, 6, 0.0, None, None),
            (8, 2, 0.5, None, 0.5), (4, 4, 0.0, None, None),
            (6, 3, 1.0, 50, 0.95), (4, 5, 0.0, None, None),
            (9, 4, 0.7, 10, None), (1, 2, 0.0, None, None),
            (10, 3, 1.5, 2, 1.0), (6, 6, 0.0, None, None),
        ]
        with active(plan):
            with server:
                before = tracecheck.trace_event_count()
                handles = []
                for i, (L, n, t, k, p) in enumerate(cases):
                    handles.append(server.submit(
                        rng.integers(0, model.cfg.vocab_size,
                                     size=(L,)).astype(np.int32),
                        max_new_tokens=n, temperature=t, top_k=k,
                        top_p=p, seed=i))
                # two deadline-doomed requests: accepted, then expired
                doomed = [server.submit(
                    np.zeros(3, np.int32), max_new_tokens=5,
                    deadline=1e-4) for _ in range(2)]

                completed, failed, hung = 0, 0, 0
                for h in handles + doomed:
                    try:
                        toks = h.result(timeout=300)
                        completed += 1
                        assert 1 <= len(toks)
                    except RequestFailed:
                        failed += 1
                    except TimeoutError:
                        hung += 1
                health = server.health()
                after = tracecheck.trace_event_count()

        # zero lost/hung: every accepted request reached a terminal
        # outcome, explicitly
        total = len(handles) + len(doomed)
        assert hung == 0
        assert completed + failed == total
        assert completed >= len(handles) - 2    # faults mostly healed
        assert failed >= 1                      # the doomed deadlines
        # the server survived the whole soak
        assert health["status"] == "serving", health
        assert server.error is None
        assert health["requeues"] >= 1          # recovery actually ran
        # compile/retrace budgets unchanged: recovery replays compiled
        # programs — warmup budgets exactly, zero traces during soak
        assert after == before, "chaos soak retraced after warmup"
        assert server.engine.trace_counts == {
            "decode_step": 1, "prefill": 3, "admit": 1, "release": 1}
        # the strict lock sanitizer observed the whole storm: zero
        # order inversions, zero guarded-field touches without locks
        lockcheck.assert_clean()
        # ... and the placement sanitizer: the engine's per-step host
        # sync happens OUTSIDE the compiled-step windows it watched
        shardcheck.assert_clean()
        assert shardcheck.site_shardings()["Engine._step"]["calls"] \
            >= 1

    def test_worker_survives_and_serves_after_faults(self):
        """After the fault plan is exhausted the same server keeps
        taking new traffic — self-healing, not merely not-crashing."""
        model, params = self._tiny()
        server = InferenceServer(model, params, max_slots=2,
                                 prompt_buckets=(4, 8))
        plan = FaultPlan([FaultSpec(site="serving.step",
                                    kind="transient", steps=(1, 2))])
        with active(plan):
            with server:
                h1 = server.submit(np.zeros(3, np.int32),
                                   max_new_tokens=4)
                try:
                    h1.result(timeout=300)
                except RequestFailed:
                    pass
                h2 = server.submit(np.ones(5, np.int32),
                                   max_new_tokens=3)
                assert len(h2.result(timeout=300)) == 3
                assert server.health()["ready"]


class TestPagedServingChaosSoak:
    """ISSUE-5 chaos satellite: the paged engine under injected
    transient faults + deadline expiries must release every pool page
    — requeue, terminal failure and mid-decode eviction all route
    through the same block-freeing release, so a fault storm cannot
    leak the KV pool (the paged analogue of "no lost requests")."""

    def _tiny(self):
        cfg = GPTConfig.tiny(position_embedding="learned",
                             scan_layers=True)
        model = GPTModel(cfg)
        params = model.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, 4), jnp.int32))
        return model, {"params": params["params"]}

    def test_soak_releases_all_blocks_no_retraces(self):
        model, params = self._tiny()
        server = InferenceServer(model, params, max_slots=3,
                                 kv_cache="paged", block_size=8,
                                 pool_tokens=256, prefill_chunk=4)
        plan = FaultPlan([
            FaultSpec(site="serving.step", kind="transient", every=5,
                      times=4),
            FaultSpec(site="serving.admit", kind="transient", step=3,
                      times=1),
        ])
        rng = np.random.default_rng(29)
        cases = [
            (3, 4, 0.0, None, None), (7, 3, 0.8, 20, None),
            (12, 5, 1.2, 5, 0.9), (2, 6, 0.0, None, None),
            (8, 2, 0.5, None, 0.5), (17, 4, 0.0, None, None),
            (6, 3, 1.0, 50, 0.95), (4, 5, 0.0, None, None),
            (9, 4, 0.7, 10, None), (1, 2, 0.0, None, None),
            (10, 3, 1.5, 2, 1.0), (6, 6, 0.0, None, None),
        ]
        with active(plan):
            with server:
                before = tracecheck.trace_event_count()
                handles = []
                for i, (L, n, t, k, p) in enumerate(cases):
                    handles.append(server.submit(
                        rng.integers(0, model.cfg.vocab_size,
                                     size=(L,)).astype(np.int32),
                        max_new_tokens=n, temperature=t, top_k=k,
                        top_p=p, seed=i))
                doomed = [server.submit(
                    np.zeros(3, np.int32), max_new_tokens=5,
                    deadline=1e-4) for _ in range(2)]
                completed, failed, hung = 0, 0, 0
                for h in handles + doomed:
                    try:
                        toks = h.result(timeout=300)
                        completed += 1
                        assert 1 <= len(toks)
                    except RequestFailed:
                        failed += 1
                    except TimeoutError:
                        hung += 1
                health = server.health()
                after = tracecheck.trace_event_count()

        total = len(handles) + len(doomed)
        assert hung == 0
        assert completed + failed == total
        assert completed >= len(handles) - 2
        assert failed >= 1
        assert health["status"] == "serving", health
        assert server.error is None
        assert health["requeues"] >= 1
        # the tentpole invariant: every page came home — no leak
        # across faults, deadlines, requeues and normal completion
        assert health["blocks_in_use"] == 0
        assert server.engine.blocks_in_use == 0
        # recovery replays compiled programs at the exact paged budget
        assert after == before, "paged chaos soak retraced"
        assert server.engine.trace_counts == {
            "decode_step": 1, "prefill_step": 1, "admit": 1,
            "release": 1}

    def test_soak_sharing_and_spec_no_leaks_token_identical(self):
        """ISSUE-7 chaos satellite: the paged soak with prefix sharing
        AND speculative decoding on.  Transient step/admit faults,
        deadline expiries, and pool-pressure preempt-requeues all ride
        refcounted shared pages and drafted steps — at the end not one
        page is leaked (``blocks_in_use == 0`` exactly: a refcount
        miscount would strand or double-free pages), every surviving
        greedy chain is token-identical to ``generate()``, and the
        trace budget is exactly the warmed 5 × 1."""
        model, params = self._tiny()
        server = InferenceServer(model, params, max_slots=3,
                                 kv_cache="paged", block_size=8,
                                 pool_tokens=160, prefill_chunk=4,
                                 admit_headroom=0, share_prefixes=True,
                                 spec_tokens=3)
        plan = FaultPlan([
            FaultSpec(site="serving.step", kind="transient", every=6,
                      times=3),
            FaultSpec(site="serving.admit", kind="transient", step=4,
                      times=1),
        ])
        rng = np.random.default_rng(71)
        pref = rng.integers(0, model.cfg.vocab_size,
                            size=(16,)).astype(np.int32)
        cases = []                   # (prompt, n, temperature, seed)
        for i in range(12):
            if i % 2 == 0:           # hot shared prompt, lookup-friendly
                prompt = np.concatenate([pref, rng.integers(
                    0, model.cfg.vocab_size,
                    size=(1 + i // 2,)).astype(np.int32)])
            else:                    # cold random traffic
                prompt = rng.integers(0, model.cfg.vocab_size,
                                      size=(3 + i,)).astype(np.int32)
            cases.append((prompt, 4 + i % 8, 0.0 if i % 3 else 0.0, i))
        with active(plan):
            with server:
                before = tracecheck.trace_event_count()
                handles = [
                    server.submit(p, max_new_tokens=n, temperature=t,
                                  seed=s)
                    for p, n, t, s in cases]
                doomed = [server.submit(
                    np.concatenate([pref, np.zeros(2, np.int32)]),
                    max_new_tokens=5, deadline=1e-4)
                    for _ in range(2)]
                completed, failed, hung = 0, 0, 0
                survivors = []
                for (p, n, _t, _s), h in zip(cases, handles):
                    try:
                        toks = h.result(timeout=300)
                        completed += 1
                        survivors.append((p, n, toks))
                    except RequestFailed:
                        failed += 1
                    except TimeoutError:
                        hung += 1
                for h in doomed:
                    try:
                        h.result(timeout=300)
                        completed += 1
                    except RequestFailed:
                        failed += 1
                    except TimeoutError:
                        hung += 1
                health = server.health()
                after = tracecheck.trace_event_count()

        assert hung == 0
        assert completed + failed == len(cases) + len(doomed)
        assert completed >= len(cases) - 2
        assert health["status"] == "serving", health
        assert server.error is None
        # the tentpole invariant under SHARING: every page came home —
        # refcounts balanced across faults, deadlines, preempts,
        # CoW forks and normal completion
        assert health["blocks_in_use"] == 0
        assert server.engine.blocks_in_use == 0
        assert server.engine.shared_blocks == 0
        # greedy chains that completed are token-identical (across
        # shared prefixes, drafted steps and any preempt-requeue)
        for p, n, toks in survivors:
            ref = np.asarray(generate(
                model, params, jnp.asarray(p[None]),
                max_new_tokens=n))[0, len(p):]
            np.testing.assert_array_equal(np.asarray(toks), ref)
        # drafting actually happened, and recovery replayed compiled
        # programs at the exact warmed budget — 5 executables, 1 each
        assert server.engine.spec_proposed > 0
        assert after == before, "sharing+spec chaos soak retraced"
        assert server.engine.trace_counts == {
            "decode_step": 1, "prefill_step": 1, "spec_step": 1,
            "admit": 1, "release": 1}

    def test_soak_quantized_sharing_and_spec_no_leaks(self):
        """ISSUE-8 chaos satellite: the sharing+spec soak with
        ``kv_dtype="int8"`` on — transient step/admit faults, deadline
        expiries and pool-pressure preempts over a QUANTIZED pool.
        Zero lost/hung; ``blocks_in_use == 0`` exactly at the end (a
        page's scale lives at its pool index and is reset at the next
        tenant's first write, so freeing the page IS freeing the scale
        — a refcount miscount would strand both); trace budget exactly
        the warmed 5 × 1 (scale maintenance rides inside the existing
        executables).  Chains here are quantized (within the accuracy
        band of ``generate()``, not bitwise — the parity-to-band claim
        is pinned by test_paged_serving's trained-proxy test); what
        this soak pins is accounting + trace discipline under fire."""
        model, params = self._tiny()
        server = InferenceServer(model, params, max_slots=3,
                                 kv_cache="paged", block_size=8,
                                 pool_tokens=160, prefill_chunk=4,
                                 admit_headroom=0, share_prefixes=True,
                                 spec_tokens=3, kv_dtype="int8")
        plan = FaultPlan([
            FaultSpec(site="serving.step", kind="transient", every=6,
                      times=3),
            FaultSpec(site="serving.admit", kind="transient", step=4,
                      times=1),
        ])
        rng = np.random.default_rng(83)
        pref = rng.integers(0, model.cfg.vocab_size,
                            size=(16,)).astype(np.int32)
        cases = []
        for i in range(12):
            if i % 2 == 0:           # hot shared prompt, lookup-friendly
                prompt = np.concatenate([pref, rng.integers(
                    0, model.cfg.vocab_size,
                    size=(1 + i // 2,)).astype(np.int32)])
            else:                    # cold random traffic
                prompt = rng.integers(0, model.cfg.vocab_size,
                                      size=(3 + i,)).astype(np.int32)
            t, k, p = [(0.0, None, None), (0.8, 20, None),
                       (1.2, 5, 0.9)][i % 3]
            cases.append((prompt, 4 + i % 8, t, k, p, i))
        with active(plan):
            with server:
                before = tracecheck.trace_event_count()
                handles = [
                    server.submit(p, max_new_tokens=n, temperature=t,
                                  top_k=k, top_p=tp, seed=s)
                    for p, n, t, k, tp, s in cases]
                doomed = [server.submit(
                    np.concatenate([pref, np.zeros(2, np.int32)]),
                    max_new_tokens=5, deadline=1e-4)
                    for _ in range(2)]
                completed, failed, hung = 0, 0, 0
                for h in handles + doomed:
                    try:
                        toks = h.result(timeout=300)
                        completed += 1
                        assert 1 <= len(toks)
                    except RequestFailed:
                        failed += 1
                    except TimeoutError:
                        hung += 1
                health = server.health()
                after = tracecheck.trace_event_count()

        assert hung == 0
        assert completed + failed == len(cases) + len(doomed)
        assert completed >= len(cases) - 2
        assert health["status"] == "serving", health
        assert server.error is None
        assert health["kv_dtype"] == "int8"
        assert health["kv_bits"] == 8
        # every page (and with it, its scale slot) came home
        assert health["blocks_in_use"] == 0
        assert server.engine.blocks_in_use == 0
        assert server.engine.shared_blocks == 0
        assert server.engine.spec_proposed > 0
        assert after == before, "quantized chaos soak retraced"
        assert server.engine.trace_counts == {
            "decode_step": 1, "prefill_step": 1, "spec_step": 1,
            "admit": 1, "release": 1}


class TestFleetChaosSoak:
    """ISSUE-6 acceptance: a 3-replica FleetRouter under mixed
    greedy/top-p/deadline traffic survives a SIGKILL-equivalent
    replica death at midpoint — zero lost/hung requests, migrated
    greedy streams token-identical to uninterrupted ``generate()``,
    survivors leak no pages, per-replica trace budgets stay exactly 4
    executables at 1 trace each — and a graceful drain under load is
    loss-free with the drained pool back to ``blocks_in_use == 0``."""

    PAGED_BUDGET = {"decode_step": 1, "prefill_step": 1, "admit": 1,
                    "release": 1}

    @pytest.fixture(autouse=True)
    def _shardcheck(self):
        # ISSUE-16: every replica's step windows run under the strict
        # placement sanitizer for the whole storm
        shardcheck.reset()
        yield
        shardcheck.uninstrument()
        shardcheck.reset()

    def _tiny(self):
        cfg = GPTConfig.tiny(position_embedding="learned",
                             scan_layers=True)
        model = GPTModel(cfg)
        params = model.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, 4), jnp.int32))
        return model, {"params": params["params"]}

    def _factory(self, model, params):
        def factory():
            # each replica is lock- AND placement-sanitized as it is
            # built — before the fleet warms/starts it, so no thread
            # can be inside a raw critical section at instrumentation
            # time (the same hook covers autoscale replacements)
            return shardcheck.instrument(lockcheck.instrument(
                InferenceServer(
                    model, params, max_slots=2, kv_cache="paged",
                    block_size=8, pool_tokens=256, prefill_chunk=4),
                strict=True), strict=True)
        return factory

    def _wait_live(self, handles, min_tokens=2, timeout=180.0):
        """Block until every handle has streamed >= min_tokens (the
        kill/drain must land mid-generation, not before or after)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if all(len(h.tokens_so_far) >= min_tokens
                   for h in handles):
                return
            time.sleep(0.01)
        raise AssertionError("streams never went live")

    def _busiest(self, router):
        live = [r for r in router._replicas
                if r is not None and not r.dead and not r.draining]
        return max(live, key=lambda r: len(r.active)).index

    def test_replica_kill_zero_loss_token_identical(self):
        model, params = self._tiny()
        vocab = model.cfg.vocab_size
        router = FleetRouter(self._factory(model, params), replicas=3,
                             probe_interval=0.05)
        lockcheck.reset()
        lockcheck.instrument(router, strict=True)
        rng = np.random.default_rng(31)
        greedy_cases = [(4, 12), (7, 10), (3, 14), (6, 11), (9, 9),
                        (2, 13)]
        sampled_cases = [(5, 8, 1.0, 0.9), (8, 6, 0.8, 0.95)]
        with router:
            before = tracecheck.trace_event_count()
            greedy = []
            for i, (L, n) in enumerate(greedy_cases):
                p = rng.integers(0, vocab, size=(L,)).astype(np.int32)
                greedy.append((p, n, router.submit(
                    p, max_new_tokens=n, seed=i)))
            sampled = [router.submit(
                rng.integers(0, vocab, size=(L,)).astype(np.int32),
                max_new_tokens=n, temperature=t, top_p=tp,
                seed=100 + i)
                for i, (L, n, t, tp) in enumerate(sampled_cases)]
            doomed = [router.submit(np.zeros(3, np.int32),
                                    max_new_tokens=5, deadline=1e-4)
                      for _ in range(2)]
            # midpoint: every greedy stream live, then kill the
            # busiest replica (SIGKILL-equivalent: worker dies, engine
            # state abandoned, nothing released)
            self._wait_live([h for _, _, h in greedy])
            victim = self._busiest(router)
            assert router._replicas[victim].active, \
                "kill must land on live streams"
            router.kill_replica(victim)

            completed, failed, hung = 0, 0, 0
            for h in ([h for _, _, h in greedy] + sampled + doomed):
                try:
                    toks = h.result(timeout=300)
                    completed += 1
                    assert len(toks) >= 1
                except RequestFailed:
                    failed += 1
                except TimeoutError:
                    hung += 1
            stats = router.stats()
            health = router.health()
            after = tracecheck.trace_event_count()
            # survivors: no page leaked, budgets exactly 4 × 1
            survivors = [r for r in router._replicas
                         if r.index != victim]
            for rep in survivors:
                assert rep.server.engine.blocks_in_use == 0, rep.index
                assert rep.server.engine.trace_counts \
                    == self.PAGED_BUDGET, rep.index

        # zero lost/hung: every accepted request reached an explicit
        # terminal outcome; only the deadline-doomed pair failed
        total = len(greedy) + len(sampled) + len(doomed)
        assert hung == 0
        assert completed + failed == total
        assert completed == len(greedy) + len(sampled)
        assert failed == len(doomed)
        # the kill actually forced migrations, and they were invisible
        # to clients: greedy output token-identical to an
        # uninterrupted generate() run
        assert stats["migrated"] >= 1
        for p, n, h in greedy:
            ref = np.asarray(generate(
                model, params, jnp.asarray(p[None]),
                max_new_tokens=n))[0, len(p):]
            np.testing.assert_array_equal(
                np.asarray(h.result(timeout=1)), ref,
                err_msg=f"migrated greedy stream diverged (L={len(p)})")
        # the fleet stayed up (2 ready survivors) and the ledger
        # balances: nothing silently lost
        assert health["replicas_ready"] == 2, health
        assert stats["submitted"] == stats["completed"] \
            + stats["failed"]
        # migration replays compiled programs — no retraces anywhere
        assert after == before, "fleet kill soak retraced"
        # and the whole storm ran under the strict lock sanitizer:
        # zero order inversions, zero unguarded guarded-field touches
        lockcheck.assert_clean()
        # ... and the placement sanitizer saw every replica decode
        # (single-chip fleet: transfer-window accounting) — clean
        shardcheck.assert_clean()
        assert shardcheck.site_shardings()[
            "PagedEngine._decode"]["calls"] >= 1

    def test_drain_under_load_is_loss_free(self):
        model, params = self._tiny()
        vocab = model.cfg.vocab_size
        router = FleetRouter(self._factory(model, params), replicas=2,
                             probe_interval=0.05)
        lockcheck.reset()
        lockcheck.instrument(router, strict=True)
        rng = np.random.default_rng(37)
        cases = [(4, 10), (6, 9), (3, 12), (8, 8), (5, 11)]
        with router:
            handles = []
            for i, (L, n) in enumerate(cases):
                p = rng.integers(0, vocab, size=(L,)).astype(np.int32)
                handles.append((p, n, router.submit(
                    p, max_new_tokens=n, seed=i)))
            self._wait_live([h for _, _, h in handles])
            victim = self._busiest(router)
            drained = router.drain(victim)
            # the drained replica released everything and is detached
            assert drained.engine.blocks_in_use == 0
            assert drained.health()["status"] == "stopped"
            assert drained.health()["draining"] is True
            assert drained.engine.trace_counts == self.PAGED_BUDGET
            # every active tenant finished or migrated — loss-free —
            # and greedy output is still token-identical
            for p, n, h in handles:
                ref = np.asarray(generate(
                    model, params, jnp.asarray(p[None]),
                    max_new_tokens=n))[0, len(p):]
                np.testing.assert_array_equal(
                    np.asarray(h.result(timeout=300)), ref)
            stats = router.stats()
            assert stats["migrated"] >= 1
            assert stats["failed"] == 0
            assert stats["completed"] == len(handles)
            # scale back up through the factory and keep serving: the
            # scale hooks ride the same drain/start machinery
            assert router.scale_up() is not None
            p = rng.integers(0, vocab, size=(5,)).astype(np.int32)
            h = router.submit(p, max_new_tokens=4)
            assert len(h.result(timeout=300)) == 4
            # the surviving + fresh replicas hold the exact budget and
            # a clean pool once everything finished
            for rep in router._replicas:
                if rep.dead:
                    continue
                assert rep.server.engine.blocks_in_use == 0
                assert rep.server.engine.trace_counts \
                    == self.PAGED_BUDGET
        # drain + scale-up ran under the strict lock AND placement
        # sanitizers too (the scale-up replica enters pre-wrapped
        # through the factory)
        lockcheck.assert_clean()
        shardcheck.assert_clean()


class TestTPFleetChaosSoak:
    """ISSUE-13 acceptance: a fleet with a TENSOR-PARALLEL replica in
    the pool (replica spanning 2 chips, KV pool sharded on kv_heads)
    survives a SIGKILL-equivalent death of exactly that replica under
    mixed greedy/sampled/deadline traffic — zero lost/hung requests,
    its tenants migrate onto the single-chip survivors with greedy
    output token-identical to uninterrupted ``generate()`` (mesh
    shapes are a per-replica detail: migration re-prefills from the
    streamed prefix, so heterogeneous layouts interoperate), and the
    survivors' pools drain to ``blocks_in_use == 0`` at the exact
    4×1 budget."""

    PAGED_BUDGET = {"decode_step": 1, "prefill_step": 1, "admit": 1,
                    "release": 1}

    @pytest.fixture(autouse=True)
    def _shardcheck(self):
        # ISSUE-16: the ONE soak where the declared-placement arm of
        # the sanitizer is live — the TP replica has a committed mesh,
        # so its pool/state output shardings are verified every step
        shardcheck.reset()
        yield
        shardcheck.uninstrument()
        shardcheck.reset()

    def test_tp_replica_kill_zero_loss_token_identical(self):
        cfg = GPTConfig.tiny(position_embedding="learned",
                             scan_layers=True)
        model = GPTModel(cfg)
        params = {"params": model.init(
            jax.random.PRNGKey(0),
            jnp.zeros((1, 4), jnp.int32))["params"]}
        vocab = cfg.vocab_size
        import itertools

        built = itertools.count()

        def factory():
            # the FIRST replica spans 2 chips; later builds (and any
            # autoscale replacement) are single-chip — a mixed-layout
            # fleet is the realistic mid-migration state
            i = next(built)
            mesh = tp_mesh(2, jax.devices()[:2]) if i == 0 else None
            return shardcheck.instrument(lockcheck.instrument(
                InferenceServer(
                    model, params, max_slots=2, kv_cache="paged",
                    block_size=8, pool_tokens=256, prefill_chunk=4,
                    mesh=mesh), strict=True), strict=True)

        router = FleetRouter(factory, replicas=3, probe_interval=0.05)
        lockcheck.reset()
        lockcheck.instrument(router, strict=True)
        rng = np.random.default_rng(41)
        # budgets long enough that NOTHING completes before the kill
        # lands — the TP replica must lose live mid-stream tenants,
        # or the migration assertion below is vacuous
        greedy_cases = [(4, 28), (7, 26), (3, 30), (6, 27), (9, 25),
                        (2, 29)]
        with router:
            # the TP replica is identifiable by its chips gauge — and
            # the fleet health must already merge it
            merged = router.health()
            assert merged["chips_per_replica"] == 2
            assert merged["chips_total"] == 4         # 2 + 1 + 1
            tp_index = next(
                r.index for r in router._replicas
                if r is not None and not r.dead
                and r.server.health()["chips_per_replica"] == 2)
            before = tracecheck.trace_event_count()
            greedy = []
            for i, (L, n) in enumerate(greedy_cases):
                p = rng.integers(0, vocab, size=(L,)).astype(np.int32)
                greedy.append((p, n, router.submit(
                    p, max_new_tokens=n, seed=i)))
            sampled = [router.submit(
                rng.integers(0, vocab, size=(6,)).astype(np.int32),
                max_new_tokens=18, temperature=0.9, top_p=0.9,
                seed=100 + i) for i in range(2)]
            doomed = [router.submit(np.zeros(3, np.int32),
                                    max_new_tokens=5, deadline=1e-4)]
            # midpoint: streams live AND the TP replica is actually
            # serving someone (the kill must cost it tenants)
            deadline = time.monotonic() + 180.0
            while time.monotonic() < deadline:
                live = all(len(h.tokens_so_far) >= 2
                           for _, _, h in greedy)
                if live and router._replicas[tp_index].active:
                    break
                time.sleep(0.01)
            assert router._replicas[tp_index].active, \
                "TP replica never took traffic — kill would be vacuous"
            router.kill_replica(tp_index)

            completed, failed, hung = 0, 0, 0
            for h in ([h for _, _, h in greedy] + sampled + doomed):
                try:
                    toks = h.result(timeout=300)
                    completed += 1
                    assert len(toks) >= 1
                except RequestFailed:
                    failed += 1
                except TimeoutError:
                    hung += 1
            stats = router.stats()
            after = tracecheck.trace_event_count()
            survivors = [r for r in router._replicas
                         if r.index != tp_index]
            for rep in survivors:
                assert rep.server.engine.blocks_in_use == 0, rep.index
                assert rep.server.engine.trace_counts \
                    == self.PAGED_BUDGET, rep.index
                assert rep.server.engine.chips_per_replica == 1

        total = len(greedy) + len(sampled) + len(doomed)
        assert hung == 0
        assert completed + failed == total
        assert completed == len(greedy) + len(sampled)
        assert failed == len(doomed)
        # the TP replica's death forced real migrations — and the
        # clients never noticed: greedy chains == generate()
        assert stats["migrated"] >= 1
        for p, n, h in greedy:
            ref = np.asarray(generate(
                model, params, jnp.asarray(p[None]),
                max_new_tokens=n))[0, len(p):]
            np.testing.assert_array_equal(
                np.asarray(h.result(timeout=1)), ref,
                err_msg=f"migrated greedy stream diverged "
                        f"(L={len(p)})")
        assert after == before, "TP fleet kill soak retraced"
        lockcheck.assert_clean()
        # placement oracle, declared arm live: the TP replica's
        # sharded pool + replicated state were compared leaf-by-leaf
        # against the committed layout on every step it served before
        # the kill — real comparisons (checked > 0), zero mismatches
        shardcheck.assert_clean()
        sites = shardcheck.site_shardings()
        tp_checked = sum(
            sites.get(f"PagedEngine.{s}", {}).get("checked", 0)
            for s in ("_decode", "_prefill", "_admit", "_release"))
        assert tp_checked > 0, \
            "TP replica served traffic but nothing was checked"


class TestPipelineKillAndResumeTrajectory:
    """ISSUE-20 chaos arm: the kill-and-resume soak on the COMPOSED
    dp × pipe 1F1B step with stage-local ZeRO-2.  Checkpoint mid-run,
    kill via an injected preemption, restore onto the
    ``pipeline_state_shardings`` placement (stage-stacked params on
    ``pipe``, masters/moments stage-local over ``data``), and the
    spliced trajectory must match the uninterrupted run.  The step is
    wrapped by the runtime placement sanitizer throughout, and the
    whole soak — reference, killed run, resumed run — holds exactly
    ONE trace of the 1F1B body (the declared retrace budget: the
    schedule is a single shape-keyed executable)."""

    STEPS = 40
    HID, DP, PP, M, MB = 16, 2, 2, 4, 2
    LAYERS = 4
    CKPT_EVERY = 8

    @pytest.fixture(autouse=True)
    def _sanitizers_strict(self):
        numcheck.reset()
        numcheck.instrument(strict=True)
        shardcheck.reset()
        yield
        shardcheck.uninstrument()
        shardcheck.reset()
        numcheck.uninstrument()
        numcheck.reset()

    def _make(self):
        from jax.sharding import Mesh, PartitionSpec as P

        from apex_tpu.parallel import ZeroConfig
        from apex_tpu.parallel import pipeline as pl

        r = np.random.default_rng(0)
        init = {"stages": (
            jnp.asarray(r.normal(size=(self.LAYERS, self.HID,
                                       self.HID)) * 0.3, jnp.float32),
            jnp.asarray(r.normal(size=(self.LAYERS, self.HID)) * 0.1,
                        jnp.float32),
            jnp.asarray(r.normal(size=(self.LAYERS, self.HID,
                                       self.HID)) * 0.3, jnp.float32),
        )}
        xs = jnp.asarray(
            r.normal(size=(4, self.DP * self.M, self.MB, self.HID)),
            jnp.float32)
        ys = jnp.asarray(
            r.normal(size=(4, self.DP * self.M, self.MB, self.HID)),
            jnp.float32)
        mesh = Mesh(np.array(jax.devices()[:self.DP * self.PP])
                    .reshape(self.DP, self.PP), ("data", "pipe"))
        tx = fused_adam(1e-2)   # ONE transform: shared static treedef

        def make_state():
            staged = {"stages": pl.stage_split(init["stages"],
                                               self.PP)}
            state = amp.initialize(
                None, staged, tx, opt_level="O0",
                zero=ZeroConfig(axis="data", axis_size=self.DP,
                                stage=2))
            state = pl.stage_local_zero(state, num_stages=self.PP)
            # committed stage placement — doubles as the
            # checkpoint-restore target
            return jax.device_put(
                state, pl.pipeline_state_shardings(state, mesh=mesh))

        def layer_apply(x, args):
            w1, b1, w2 = args
            h = jnp.tanh(x @ w1 + b1)
            return x + h @ w2, None

        def stage_fn(params, x):
            x, _ = jax.lax.scan(layer_apply, x, params)
            return x

        traces = [0]

        def body(state, mbs, labels):
            traces[0] += 1

            def loss_fn(out, i):
                yl = jax.lax.dynamic_index_in_dim(labels, i, 0,
                                                  keepdims=False)
                return jnp.mean((out - yl) ** 2)

            loss, grads = pl.run_1f1b(stage_fn, loss_fn,
                                      state.params["stages"], mbs)
            grads = pl.sync_grad_overflow({"stages": grads})
            new_state, _ = state.apply_gradients(grads=grads)
            return new_state, jax.lax.pmean(loss, "data")

        state0 = make_state()
        # donate=False: the checkpointer's async save may still be
        # reading the state buffers when the next step runs
        step = pl.wrap_pipeline_step(
            body, state=state0, mesh=mesh,
            batch_specs=(P("data"), P("data")), donate=False)

        # runtime placement oracle (ISSUE-16): the declared pipeline
        # layout — stage-stacked params on pipe, stage-local masters
        # on (pipe, data), replicated pmean'd loss — verified against
        # every compiled step's actual outputs
        declared = (pl.pipeline_state_shardings(state0, mesh=mesh),
                    jax.sharding.NamedSharding(mesh, P()))
        step = shardcheck.wrap_step(step, declared=declared,
                                    mesh=mesh,
                                    name="pipeline.train_step",
                                    strict=True)

        def loop_step(state, batch):
            state, loss = step(state, batch[0], batch[1])
            return state, {"loss": loss}

        def data_fn(i):
            return (xs[i % 4], ys[i % 4])

        return make_state, step, loop_step, data_fn, traces

    def _rows(self, writer):
        return {s: r["loss"] for s, r in writer.history}

    def test_pipeline_preempt_resume_matches_uninterrupted(
            self, tmp_path):
        make_state, step, loop_step, data_fn, traces = self._make()

        # ------------------------- the uninterrupted reference run
        state = make_state()
        ref = []
        for i in range(self.STEPS):
            x, y = data_fn(i)
            state, loss = step(state, x, y)
            ref.append(float(loss))
        assert np.all(np.isfinite(ref))
        assert ref[-1] < ref[0]

        # ------------------- run 1: killed by injected preemption
        ckpt_dir = str(tmp_path / "ckpts")
        kill_at = 17
        writer1 = MetricsWriter(sink=lambda s, m: None)
        loop1 = ResilientLoop(
            loop_step,
            checkpointer=ResilientCheckpointer(ckpt_dir, keep=3),
            checkpoint_every=self.CKPT_EVERY,
            scalars_of=lambda aux: {"loss": aux["loss"]},
            metrics=writer1)
        plan = FaultPlan([FaultSpec(site="train.step", kind="preempt",
                                    step=kill_at, times=1)])
        with active(plan):
            _carry, report1 = loop1.run(make_state(), data_fn,
                                        self.STEPS)
        assert report1.preempted
        assert report1.final_step == kill_at

        # ------------------- run 2: auto-resume onto the STAGE
        # placement (the target is the pipeline_state_shardings-
        # placed state)
        writer2 = MetricsWriter(sink=lambda s, m: None)
        loop2 = ResilientLoop(
            loop_step,
            checkpointer=ResilientCheckpointer(ckpt_dir, keep=3),
            checkpoint_every=self.CKPT_EVERY,
            scalars_of=lambda aux: {"loss": aux["loss"]},
            metrics=writer2)
        carry2, report2 = loop2.run(make_state(), data_fn, self.STEPS)
        assert report2.resumed_from == kill_at
        assert report2.final_step == self.STEPS
        assert not report2.preempted

        # stage-local masters came back ON their (pipe, data) rows:
        # each chip holds one stage's one data-shard
        for leaf in jax.tree.leaves(carry2.opt_state.master):
            assert tuple(leaf.sharding.spec)[:2] == ("pipe", "data")
            assert leaf.sharding.shard_shape(leaf.shape)[:2] == (1, 1)
            assert leaf.dtype == jnp.float32

        # ------------------------- the spliced trajectory matches
        rows1, rows2 = self._rows(writer1), self._rows(writer2)
        spliced = [rows1[i] if i <= report2.resumed_from else rows2[i]
                   for i in range(1, self.STEPS + 1)]
        np.testing.assert_allclose(
            spliced, ref, rtol=0, atol=1e-5,
            err_msg="pipelined resume diverged from uninterrupted")

        # ------------------- the oracles: numerics clean, placement
        # clean, and the whole soak held ONE trace of the 1F1B body
        jax.effects_barrier()
        numcheck.assert_clean()
        shardcheck.assert_clean()
        psite = shardcheck.site_shardings()["pipeline.train_step"]
        assert psite["checked"] > 0
        assert psite["mismatched"] == 0
        assert traces[0] == 1, (
            f"1F1B body traced {traces[0]} times across the soak — "
            f"the declared budget is ONE shape-keyed executable")
