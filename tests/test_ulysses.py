"""Ulysses (all-to-all) sequence parallelism vs single-device reference.

Same golden pattern as the ring-attention suite: the sharded
implementation is asserted against the eager composition on the
gathered sequence, on the 8-virtual-device CPU mesh (the reference has
no context parallelism at all — SURVEY.md §2.6 checklist)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.core import mesh as mesh_lib
from apex_tpu.core.mesh import CONTEXT_AXIS, DATA_AXIS
from apex_tpu.ops.attention import attention_reference
from apex_tpu.parallel.ulysses import (
    ulysses_attention,
    ulysses_self_attention,
)


@pytest.fixture
def cp_mesh():
    m = mesh_lib.initialize_mesh(context_parallel_size=4,
                                 data_parallel_size=2)
    yield m
    mesh_lib.destroy_mesh()


def _mk_qkv(rng, b, s, h, d, hk=None):
    hk = h if hk is None else hk
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, hk, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, hk, d)), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_reference(cp_mesh, rng, causal):
    q, k, v = _mk_qkv(rng, 2, 32, 4, 8)
    want = attention_reference(q, k, v, causal=causal)
    got = jax.jit(functools.partial(
        ulysses_self_attention, mesh=cp_mesh, causal=causal))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_ulysses_gqa_split(cp_mesh, rng):
    # hk=8, cp=4: kv heads split naturally (2 per device)
    q, k, v = _mk_qkv(rng, 2, 32, 8, 8, hk=8)
    want = attention_reference(q, k, v, causal=True)
    got = ulysses_self_attention(q, k, v, mesh=cp_mesh, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_ulysses_gqa_repeat(cp_mesh, rng):
    # hk=2 < cp=4: kv heads repeated to cp; q-group alignment must hold
    q, k, v = _mk_qkv(rng, 2, 32, 8, 8, hk=2)
    want = attention_reference(q, k, v, causal=True)
    got = ulysses_self_attention(q, k, v, mesh=cp_mesh, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_ulysses_sliding_window(cp_mesh, rng):
    # the banded flash grid rides through the all-to-all layout
    q, k, v = _mk_qkv(rng, 1, 64, 4, 8)
    want = attention_reference(q, k, v, causal=True, window=10)
    got = ulysses_self_attention(q, k, v, mesh=cp_mesh, causal=True,
                                 window=10)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_grads_match_reference(cp_mesh, rng, causal):
    q, k, v = _mk_qkv(rng, 1, 32, 4, 8)

    def loss_sharded(q, k, v):
        o = ulysses_self_attention(q, k, v, mesh=cp_mesh,
                                   causal=causal)
        return jnp.sum(jnp.tanh(o))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.tanh(
            attention_reference(q, k, v, causal=causal)))

    gs = jax.grad(loss_sharded, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gs, gr, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-5,
            err_msg=f"d{name}")


def test_ulysses_gqa_repeat_grads(cp_mesh, rng):
    q, k, v = _mk_qkv(rng, 1, 32, 8, 8, hk=2)

    def loss_sharded(q, k, v):
        o = ulysses_self_attention(q, k, v, mesh=cp_mesh, causal=True)
        return jnp.sum(jnp.tanh(o))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.tanh(
            attention_reference(q, k, v, causal=True)))

    gs = jax.grad(loss_sharded, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gs, gr, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-5,
            err_msg=f"d{name}")


def test_ulysses_composes_with_data_parallel(cp_mesh, rng):
    q, k, v = _mk_qkv(rng, 2, 32, 4, 8)
    want = attention_reference(q, k, v, causal=True)
    got = ulysses_self_attention(q, k, v, mesh=cp_mesh, causal=True,
                                 batch_spec=DATA_AXIS)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_ulysses_head_divisibility_errors(cp_mesh, rng):
    # h=6 not divisible by cp=4
    q, k, v = _mk_qkv(rng, 1, 32, 6, 8)
    with pytest.raises(ValueError, match="divisible"):
        ulysses_self_attention(q, k, v, mesh=cp_mesh, causal=True)
    # hk=3: neither hk % cp == 0 nor cp % hk == 0
    q, k, v = _mk_qkv(rng, 1, 32, 12, 8, hk=3)
    with pytest.raises(ValueError, match="kv heads"):
        ulysses_self_attention(q, k, v, mesh=cp_mesh, causal=True)


def test_ulysses_agrees_with_ring(cp_mesh, rng):
    """The two CP strategies are exact: they must agree with each
    other, not just with the reference."""
    from apex_tpu.parallel.ring_attention import ring_self_attention

    q, k, v = _mk_qkv(rng, 2, 32, 4, 8)
    u = ulysses_self_attention(q, k, v, mesh=cp_mesh, causal=True)
    r = ring_self_attention(q, k, v, mesh=cp_mesh, causal=True)
    np.testing.assert_allclose(np.asarray(u), np.asarray(r),
                               atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("seed_form", ["int", "key", "int32_array"])
def test_ulysses_dropout_decorrelated_across_shards(cp_mesh, rng,
                                                    seed_form):
    """In-kernel dropout under Ulysses folds the shard index into the
    seed (round-4 advisor finding): with identical per-head q/k/v,
    global heads on DIFFERENT context shards must draw different
    masks — without the fold, every shard's local lane indices
    coincide and heads h/cp apart would share one mask.  All seed
    forms fused_attention accepts must survive the fold."""
    b, s, h, d = 1, 32, 4, 8          # cp=4 -> one head per shard
    one = jnp.asarray(rng.standard_normal((b, s, 1, d)), jnp.float32)
    q = jnp.broadcast_to(one, (b, s, h, d))
    k = jnp.broadcast_to(one, (b, s, h, d))
    v = jnp.broadcast_to(one, (b, s, h, d))
    seed = {"int": 7, "key": jax.random.PRNGKey(7),
            "int32_array": jnp.int32(7)}[seed_form]
    spec = P(None, CONTEXT_AXIS, None, None)

    @functools.partial(
        jax.shard_map, mesh=cp_mesh, in_specs=(spec, spec, spec),
        out_specs=spec, axis_names={CONTEXT_AXIS})
    def run(ql, kl, vl):
        return ulysses_attention(ql, kl, vl, CONTEXT_AXIS,
                                 dropout_rate=0.5, dropout_rng=seed)

    out = np.asarray(run(q, k, v))     # (b, s, h, d)
    assert np.isfinite(out).all()
    for i in range(h):
        for j in range(i + 1, h):
            assert not np.allclose(out[:, :, i], out[:, :, j]), (
                f"heads {i} and {j} (different shards) share a "
                f"dropout mask ({seed_form})")
