"""Ring attention (context parallelism) vs single-device reference.

Golden pattern (SURVEY.md §4): the sharded collective implementation is
asserted against the eager composition on the gathered sequence — here
on 8 virtual CPU devices, beyond what the reference's 2-real-GPU
distributed tests could do (and the reference has no context
parallelism at all to test).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.core import mesh as mesh_lib
from apex_tpu.core.mesh import CONTEXT_AXIS, DATA_AXIS
from apex_tpu.ops.attention import attention_reference
from apex_tpu.parallel.ring_attention import (
    ring_attention,
    ring_self_attention,
)


@pytest.fixture
def cp_mesh():
    m = mesh_lib.initialize_mesh(context_parallel_size=4,
                                 data_parallel_size=2)
    yield m
    mesh_lib.destroy_mesh()


def _mk_qkv(rng, b, s, h, d, hk=None):
    hk = h if hk is None else hk
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, hk, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, hk, d)), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_reference(cp_mesh, rng, causal):
    q, k, v = _mk_qkv(rng, 2, 32, 4, 8)
    want = attention_reference(q, k, v, causal=causal)
    got = jax.jit(functools.partial(
        ring_self_attention, mesh=cp_mesh, causal=causal))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_ring_gqa(cp_mesh, rng):
    q, k, v = _mk_qkv(rng, 2, 32, 8, 8, hk=2)
    want = attention_reference(q, k, v, causal=True)
    got = ring_self_attention(q, k, v, mesh=cp_mesh, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.l0
def test_ring_grads_match_reference(cp_mesh, rng, causal):
    q, k, v = _mk_qkv(rng, 1, 32, 2, 8)
    w = jnp.asarray(rng.standard_normal((2, 32, 2, 8)), jnp.float32)

    def ref_loss(q, k, v):
        o = attention_reference(q, k, v, causal=causal)
        return jnp.sum(o * o) / o.size

    def ring_loss(q, k, v):
        o = ring_self_attention(q, k, v, mesh=cp_mesh, causal=causal)
        return jnp.sum(o * o) / o.size

    want = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    got = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
    for g, wgrad in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(wgrad),
                                   atol=1e-4, rtol=1e-4)


def test_ring_gqa_grads_match_reference(cp_mesh, rng):
    """Exercises the g>1 backward einsums (group-dim reduction in
    dk/dv, grouped dq) that the MHA grad test cannot reach."""
    q, k, v = _mk_qkv(rng, 1, 32, 4, 8, hk=2)

    def ref_loss(q, k, v):
        o = attention_reference(q, k, v, causal=True)
        return jnp.sum(o * o) / o.size

    def ring_loss(q, k, v):
        o = ring_self_attention(q, k, v, mesh=cp_mesh, causal=True)
        return jnp.sum(o * o) / o.size

    want = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    got = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
    for g, wgrad in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(wgrad),
                                   atol=1e-4, rtol=1e-4)


def test_ring_causal_uneven_lengths(cp_mesh, rng):
    """Causal mask must bottom-align when global Sk > Sq (KV-cache
    style), matching attention_reference's ``k <= q + (Sk - Sq)``."""
    b, h, d = 1, 2, 8
    q = jnp.asarray(rng.standard_normal((b, 16, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, 32, h, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, 32, h, d)), jnp.float32)
    want = attention_reference(q, k, v, causal=True)
    got = ring_self_attention(q, k, v, mesh=cp_mesh, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_ring_composes_with_data_parallel(cp_mesh, rng):
    q, k, v = _mk_qkv(rng, 4, 16, 2, 8)
    want = attention_reference(q, k, v, causal=True)
    got = ring_self_attention(q, k, v, mesh=cp_mesh, causal=True,
                              batch_spec=DATA_AXIS)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_ring_inside_user_shard_map(cp_mesh, rng):
    """Direct shard_map use (the form model code embeds)."""
    q, k, v = _mk_qkv(rng, 2, 32, 4, 8)

    @functools.partial(
        jax.shard_map, mesh=cp_mesh,
        in_specs=(P(None, CONTEXT_AXIS), P(None, CONTEXT_AXIS),
                  P(None, CONTEXT_AXIS)),
        out_specs=P(None, CONTEXT_AXIS), axis_names={CONTEXT_AXIS})
    def run(ql, kl, vl):
        return ring_attention(ql, kl, vl, CONTEXT_AXIS, True, None)

    want = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(run(q, k, v)),
                               np.asarray(want), atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_remat_grads_match(cp_mesh, rng, causal):
    """remat=True saves only (q,k,v) and recomputes (o,lse) in the
    backward ring — grads must be identical to the saving mode."""
    q, k, v = _mk_qkv(rng, 1, 32, 4, 8, hk=2)

    def loss(remat):
        def f(q, k, v):
            o = ring_self_attention(q, k, v, mesh=cp_mesh, causal=causal,
                                    remat=remat)
            return jnp.sum(o * o) / o.size
        return f

    want = jax.jit(jax.grad(loss(False), argnums=(0, 1, 2)))(q, k, v)
    got = jax.jit(jax.grad(loss(True), argnums=(0, 1, 2)))(q, k, v)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   atol=1e-6, rtol=1e-6)


def test_ring_cp8_long_sequence(rng):
    """cp=8 (whole virtual mesh) with a long sequence — the scale CP
    exists for; exercises the scanned ring at full mesh width."""
    m = mesh_lib.initialize_mesh(context_parallel_size=8)
    try:
        q, k, v = _mk_qkv(rng, 1, 512, 2, 16)
        want = attention_reference(q, k, v, causal=True)
        got = jax.jit(functools.partial(
            ring_self_attention, mesh=m, causal=True))(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-5, rtol=2e-5)
    finally:
        mesh_lib.destroy_mesh()


def test_ring_hlo_flat_in_cp(rng):
    """The ring is a lax.scan, so compiled-program size must be ~flat
    as cp grows (a Python unroll would be O(cp)) — round-1 verdict
    weak-item 5."""
    sizes = {}
    for cp in (2, 8):
        m = mesh_lib.initialize_mesh(context_parallel_size=cp)
        try:
            q, k, v = _mk_qkv(rng, 1, 64, 2, 8)

            def loss(q, k, v):
                o = ring_self_attention(q, k, v, mesh=m, causal=True)
                return jnp.sum(o * o)

            lowered = jax.jit(jax.grad(loss, argnums=(0, 1, 2))).lower(
                q, k, v)
            sizes[cp] = len(lowered.as_text())
        finally:
            mesh_lib.destroy_mesh()
    assert sizes[8] < 1.3 * sizes[2], sizes


def test_ring_bf16(cp_mesh, rng):
    q, k, v = _mk_qkv(rng, 2, 32, 2, 8)
    q, k, v = (x.astype(jnp.bfloat16) for x in (q, k, v))
    want = attention_reference(q, k, v, causal=True)
    got = ring_self_attention(q, k, v, mesh=cp_mesh, causal=True)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        atol=3e-2, rtol=3e-2)
