"""Unit tier for the runtime numerics sanitizer
(``apex_tpu.utils.numcheck``) — the dynamic twin of graftlint's
precision pass, the way ``tests/test_lockcheck.py`` pins the lock
sanitizer: instrument idempotence, strict mode in both directions
(a planted master-weight breach is recorded strict-only), the
``APEX_TPU_NUMCHECK`` env gate, underflow detection on a synthetic
tiny-grad step, dtype histograms at the amp cast boundaries, and the
loss-scale growth/backoff counters numcheck reads.

Every test instruments inside a try/finally ``uninstrument()`` so the
process-wide hooks never leak into the rest of the suite.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from apex_tpu import amp
from apex_tpu.core.loss_scale import DynamicLossScale
from apex_tpu.core.precision import PrecisionPolicy, tree_cast
from apex_tpu.utils import numcheck
from apex_tpu.utils.metrics import counters


def _make_state(opt_level="O2", half_dtype=jnp.float16, **overrides):
    def apply_fn(p, x):
        return x @ p["w"]

    params = {"w": jnp.ones((4, 4), jnp.float32),
              "b": jnp.zeros((4,), jnp.float32)}
    return amp.initialize(apply_fn, params, optax.sgd(0.1),
                          opt_level=opt_level, half_dtype=half_dtype,
                          **overrides)


def _grads(zero_rows=0, value=1e-3):
    g = jnp.full((4, 4), value, jnp.float32)
    if zero_rows:
        g = g.at[:zero_rows].set(0.0)
    return {"w": g, "b": jnp.full((4,), value, jnp.float32)}


@pytest.fixture(autouse=True)
def _isolated():
    numcheck.reset()
    yield
    numcheck.uninstrument()
    numcheck.reset()


class TestInstrument:
    def test_idempotent_single_wrapper_and_single_count(self):
        numcheck.instrument(strict=True)
        numcheck.instrument(strict=True)        # second call: no-op
        state = _make_state()
        state.apply_gradients(grads=_grads())
        jax.effects_barrier()
        s = numcheck.summary()
        # one step -> exactly one grad-stat emission (a double wrap
        # would double-count)
        assert s["grad_stat_steps"] == 1
        from apex_tpu.core.train_state import MixedPrecisionTrainState
        fn = MixedPrecisionTrainState.apply_gradients
        assert getattr(fn, "_numcheck_wrapper", False)

    def test_uninstrument_restores_originals(self):
        from apex_tpu.core.train_state import MixedPrecisionTrainState
        orig = MixedPrecisionTrainState.apply_gradients
        numcheck.instrument(strict=True)
        assert MixedPrecisionTrainState.apply_gradients is not orig
        numcheck.uninstrument()
        assert MixedPrecisionTrainState.apply_gradients is orig
        # and a fresh instrument works again after uninstrument
        numcheck.instrument(strict=True)
        assert MixedPrecisionTrainState.apply_gradients is not orig

    def test_env_gate(self, monkeypatch):
        monkeypatch.setenv("APEX_TPU_NUMCHECK", "strict")
        assert numcheck.env_strict()
        numcheck.instrument()                   # strict=None follows env
        bad = _make_state().replace(
            params=tree_cast(_make_state().params, jnp.bfloat16))
        bad.apply_gradients(grads=tree_cast(_grads(), jnp.bfloat16))
        jax.effects_barrier()
        assert numcheck.reports()               # env made it strict
        numcheck.uninstrument()
        monkeypatch.delenv("APEX_TPU_NUMCHECK")
        assert not numcheck.env_strict()


class TestStrictBothDirections:
    def test_master_weight_breach_recorded_strict(self):
        numcheck.instrument(strict=True)
        state = _make_state()                   # O2: fp32 masters
        bad = state.replace(params=tree_cast(state.params, jnp.bfloat16))
        bad.apply_gradients(grads=tree_cast(_grads(), jnp.bfloat16))
        jax.effects_barrier()
        found = numcheck.reports()
        assert len(found) == 1
        assert "non-fp32 master weights" in found[0]
        assert "master-weight-violation" in found[0]   # the static twin
        with pytest.raises(numcheck.NumCheckError):
            numcheck.assert_clean()
        # deduped: the same breach again is still one report
        bad.apply_gradients(grads=tree_cast(_grads(), jnp.bfloat16))
        jax.effects_barrier()
        assert len(numcheck.reports()) == 1

    def test_same_breach_not_recorded_non_strict(self):
        numcheck.instrument(strict=False)
        state = _make_state()
        bad = state.replace(params=tree_cast(state.params, jnp.bfloat16))
        bad.apply_gradients(grads=tree_cast(_grads(), jnp.bfloat16))
        jax.effects_barrier()
        assert numcheck.reports() == []
        numcheck.assert_clean()                 # observe-only: clean
        # ...but observation still happened
        assert numcheck.summary()["grad_stat_steps"] == 1

    def test_clean_run_is_clean_strict(self):
        numcheck.instrument(strict=True)
        state = _make_state()
        state, finite = state.apply_gradients(grads=_grads())
        jax.effects_barrier()
        assert bool(finite)
        numcheck.assert_clean()


class TestGradStats:
    def test_underflow_fraction_on_synthetic_tiny_grad_step(self):
        numcheck.instrument(strict=True)
        state = _make_state()
        # 2 of 4 rows of w flushed to exactly zero (the fp16 underflow
        # signature after loss-scale multiply): 8/16 w-elems + 0/4
        # b-elems -> 8/20 overall
        state.apply_gradients(grads=_grads(zero_rows=2))
        jax.effects_barrier()
        s = numcheck.summary()
        assert s["grad_total_elems"] == 20
        assert s["grad_zero_elems"] == 8
        assert s["grad_underflow_frac"] == pytest.approx(0.4)
        # mirrored onto the shared counters for bench emissions
        assert counters.get("numcheck.grad_total") >= 20

    def test_nonfinite_grads_counted_not_flagged(self):
        # a non-finite scaled grad is the dynamic scaler's expected
        # diet: the step skips, numcheck counts, nothing is flagged
        numcheck.instrument(strict=True)
        state = _make_state()
        g = _grads()
        g["w"] = g["w"].at[0, 0].set(jnp.inf)
        new_state, finite = state.apply_gradients(grads=g)
        jax.effects_barrier()
        assert not bool(finite)
        np.testing.assert_array_equal(      # step skipped: params kept
            new_state.params["w"], state.params["w"])
        s = numcheck.summary()
        assert s["nonfinite_grad_steps"] == 1
        assert s["nonfinite_grad_elems"] >= 1
        numcheck.assert_clean()

    def test_stats_recorded_under_jit(self):
        numcheck.instrument(strict=True)
        state = _make_state()

        @jax.jit
        def step(st, g):
            return st.apply_gradients(grads=g)

        for _ in range(3):
            state, _ = step(state, _grads(zero_rows=1))
        jax.effects_barrier()
        s = numcheck.summary()
        assert s["grad_stat_steps"] == 3
        assert s["grad_underflow_frac"] == pytest.approx(4 / 20)
        numcheck.assert_clean()


class TestCastBoundaries:
    def test_dtype_histograms_at_cast_sites(self):
        numcheck.instrument(strict=False)
        policy = PrecisionPolicy.O2(half_dtype=jnp.bfloat16)
        tree = {"w": jnp.ones((2, 2), jnp.float32)}
        policy.cast_to_compute(tree)
        hists = numcheck.site_histograms()
        assert hists["cast_to_compute.in"] == {"float32": 1}
        assert hists["cast_to_compute.out"] == {"bfloat16": 1}

    def test_fp16_downcast_overflow_is_a_strict_violation(self):
        numcheck.instrument(strict=True)
        policy = PrecisionPolicy.O3(half_dtype=jnp.float16)
        big = {"w": jnp.full((2, 2), 1e30, jnp.float32)}  # > fp16 max
        policy.cast_to_param(big)
        jax.effects_barrier()
        found = numcheck.reports()
        assert len(found) == 1 and "downcast overflow" in found[0]

    def test_bf16_downcast_cannot_overflow(self):
        # bf16 shares fp32's exponent range: same magnitudes, clean
        numcheck.instrument(strict=True)
        policy = PrecisionPolicy.O3(half_dtype=jnp.bfloat16)
        big = {"w": jnp.full((2, 2), 1e30, jnp.float32)}
        policy.cast_to_param(big)
        jax.effects_barrier()
        numcheck.assert_clean()


class TestLossScaleEvents:
    def test_growth_and_backoff_counted_and_read_by_summary(self):
        before_g = counters.get("amp.loss_scale.growth")
        before_b = counters.get("amp.loss_scale.backoff")
        ls = DynamicLossScale(growth_interval=2)
        st = ls.init()
        st = ls.adjust(st, jnp.asarray(True))
        st = ls.adjust(st, jnp.asarray(True))       # clean x2 -> growth
        st = ls.adjust(st, jnp.asarray(False))      # overflow -> backoff
        jax.effects_barrier()
        assert counters.get("amp.loss_scale.growth") == before_g + 1
        assert counters.get("amp.loss_scale.backoff") == before_b + 1
        s = numcheck.summary()
        assert s["loss_scale_growth"] >= before_g + 1
        assert s["loss_scale_backoff"] >= before_b + 1

    def test_no_growth_event_when_pinned_at_max_scale(self):
        # review regression: the growth event is derived from the
        # actual scale change, not the trigger condition — a healthy
        # run saturated at max_scale must not log a fake growth every
        # interval forever
        before = counters.get("amp.loss_scale.growth")
        ls = DynamicLossScale(init_scale=2.0 ** 24,
                              max_scale=2.0 ** 24, growth_interval=1)
        st = ls.init()
        st = ls.adjust(st, jnp.asarray(True))   # trigger fires, pinned
        jax.effects_barrier()
        assert float(st.loss_scale) == 2.0 ** 24
        assert counters.get("amp.loss_scale.growth") == before

    def test_count_events_false_is_silent(self):
        before = counters.get("amp.loss_scale.backoff")
        ls = DynamicLossScale(count_events=False)
        st = ls.adjust(ls.init(), jnp.asarray(False))
        jax.effects_barrier()
        assert float(st.loss_scale) == 2.0 ** 15     # still backs off
        assert counters.get("amp.loss_scale.backoff") == before

    def test_reset_clears_stats_but_not_instrumentation(self):
        numcheck.instrument(strict=True)
        state = _make_state()
        state.apply_gradients(grads=_grads())
        jax.effects_barrier()
        assert numcheck.summary()["grad_stat_steps"] == 1
        numcheck.reset()
        assert numcheck.summary()["grad_stat_steps"] == 0
        state.apply_gradients(grads=_grads())   # still instrumented
        jax.effects_barrier()
        assert numcheck.summary()["grad_stat_steps"] == 1
