"""tracecheck runtime guard — the dynamic oracle behind graftlint.

Covers the ISSUE satellite: a shape-polymorphic call pattern under
``retrace_guard`` trips at ``max_traces``, while a stable-signature
train step compiles once and never trips.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from apex_tpu.utils import tracecheck
from apex_tpu.utils.tracecheck import RetraceError, retrace_guard


class TestRetraceGuard:
    def test_shape_polymorphic_calls_trip_at_max_traces(self):
        step = retrace_guard(lambda x: x * 2, max_traces=2, name="poly")
        step(jnp.ones((4,)))
        step(jnp.ones((8,)))          # second shape: still within budget
        assert step.trace_count == 2
        with pytest.raises(RetraceError) as exc:
            step(jnp.ones((16,)))     # third distinct shape: storm
        msg = str(exc.value)
        assert "poly" in msg and "max_traces=2" in msg
        # the error names the rejected signature and the compiled ones
        assert "[16]" in msg and "[4]" in msg

    def test_post_budget_calls_do_not_grow_state(self):
        # a harness catching RetraceError and retrying must not inflate
        # the count (failed traces are never cached by jit)
        f = retrace_guard(lambda x: x, max_traces=1)
        f(jnp.ones((2,)))
        for _ in range(3):
            with pytest.raises(RetraceError):
                f(jnp.ones((5,)))
        assert f.trace_count == 1
        assert len(f.signatures) == 1

    def test_body_exception_propagates_without_consuming_budget(self):
        # a failed trace is never jit-cached, so it must not count:
        # retrying a call whose body raises a real error has to keep
        # raising THAT error, not a spurious RetraceError
        def bad(x):
            raise ValueError("boom")

        f = retrace_guard(bad, max_traces=1)
        for _ in range(3):
            with pytest.raises(ValueError, match="boom"):
                f(jnp.ones((2,)))
        assert f.trace_count == 0
        assert f.signatures == []

    def test_stable_train_step_compiles_once(self):
        tx = optax.sgd(1e-2)
        params = {"w": jnp.ones((8, 8)), "b": jnp.zeros((8,))}
        opt_state = tx.init(params)

        @retrace_guard(max_traces=1)
        def train_step(params, opt_state, x, y):
            def loss_fn(p):
                return jnp.mean((x @ p["w"] + p["b"] - y) ** 2)
            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = tx.update(grads, opt_state)
            return optax.apply_updates(params, updates), opt_state, loss

        x = jnp.ones((16, 8))
        y = jnp.zeros((16, 8))
        losses = []
        for _ in range(5):
            params, opt_state, loss = train_step(params, opt_state, x, y)
            losses.append(float(loss))
        assert train_step.trace_count == 1
        assert losses[-1] < losses[0]  # and it actually trains

    def test_dtype_change_counts_as_new_trace(self):
        f = retrace_guard(lambda x: x + 1, max_traces=1)
        f(jnp.ones((4,), jnp.float32))
        with pytest.raises(RetraceError):
            f(jnp.ones((4,), jnp.bfloat16))

    def test_cache_hits_do_not_count(self):
        f = retrace_guard(lambda x: x + 1, max_traces=1)
        for _ in range(10):
            f(jnp.ones((4,)))
        assert f.trace_count == 1

    def test_decorator_without_arguments(self):
        @retrace_guard
        def f(x):
            return x * 3

        np.testing.assert_allclose(f(jnp.ones((2,))), 3.0)
        assert f.trace_count == 1 and f.max_traces == 2

    def test_reset_restores_budget(self):
        f = retrace_guard(lambda x: x, max_traces=1)
        f(jnp.ones((2,)))
        with pytest.raises(RetraceError):
            f(jnp.ones((3,)))
        f.reset()
        assert f.trace_count == 0 and f.signatures == []
        f(jnp.ones((3,)))             # fresh budget, no raise
        assert f.trace_count == 1

    def test_jit_kwargs_pass_through(self):
        f = retrace_guard(lambda n: jnp.zeros((n,)), max_traces=1,
                          static_argnums=(0,))
        assert f(4).shape == (4,)

    def test_rejects_already_jitted_function(self):
        jitted = jax.jit(lambda x: x)
        with pytest.raises(TypeError, match="un-jitted"):
            retrace_guard(jitted)

    def test_max_traces_must_be_positive(self):
        with pytest.raises(ValueError):
            retrace_guard(lambda x: x, max_traces=0)

    def test_wrap_jit_false_counts_every_python_execution(self):
        f = retrace_guard(lambda x: x + 1, max_traces=2, wrap_jit=False)
        f(jnp.ones((2,)))
        f(jnp.ones((2,)))             # no jit cache: body runs again
        with pytest.raises(RetraceError):
            f(jnp.ones((2,)))


class TestTraceEventCounter:
    def test_counter_sees_traces_and_ignores_cache_hits(self):
        available = tracecheck.install_trace_counter()
        if not available:
            pytest.skip("jax.monitoring listener API unavailable")

        @jax.jit
        def f(x):
            return x * 2

        tracecheck.reset_trace_event_count()
        f(jnp.ones((7,)))                       # miss: traces
        after_first = tracecheck.trace_event_count()
        assert after_first >= 1
        f(jnp.ones((7,)))                       # hit: no new traces
        assert tracecheck.trace_event_count() == after_first
        f(jnp.ones((9,)))                       # new shape: traces again
        assert tracecheck.trace_event_count() > after_first

    def test_reset_zeroes(self):
        tracecheck.reset_trace_event_count()
        assert tracecheck.trace_event_count() == 0

    def test_exported_from_utils_package(self):
        from apex_tpu import utils
        assert utils.retrace_guard is retrace_guard
        assert utils.RetraceError is RetraceError
