"""L1-style loss-trajectory artifact (ISSUE-3 satellite / round-5
verdict Missing #5): a few-hundred-step CPU training run comparing the
O0 (pure fp32) and O2 (bf16 compute + fp32 masters + dynamic loss
scaling) trajectories on the testing-commons toy GPT.

The reference's L1 tests train the standalone models under each opt
level and assert the loss curves agree within a band — the claim being
that mixed precision changes *arithmetic*, not *optimization*.  Here:
same data order, same init, FusedAdam, 300 steps; the trajectories
must (a) both decrease substantially (the model actually trains) and
(b) stay inside an agreement band wide enough for bf16 noise but far
tighter than the training signal itself.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu import amp
from apex_tpu.models import gpt_loss_fn
from apex_tpu.optim import fused_adam
from apex_tpu.transformer.testing import standalone_gpt


@pytest.mark.slow
def test_o0_vs_o2_loss_trajectory_agreement():
    steps = 300
    b, s = 8, 32

    model, init_params = standalone_gpt(seed=0, max_seq_len=s)
    vocab = model.cfg.vocab_size
    data_key = jax.random.PRNGKey(1234)
    # a FIXED pool of 4 batches, cycled: fresh random tokens every
    # step would leave nothing learnable (loss pinned at ≈ ln V) —
    # the trajectory signal here is memorization speed
    n_pool = 4
    ids = jax.random.randint(data_key, (n_pool, b, s + 1), 0, vocab,
                             jnp.int32)

    def run(opt_level):
        state = amp.initialize(
            model.apply, {"params": init_params},
            fused_adam(3e-4),
            opt_level=opt_level,
            half_dtype=jnp.bfloat16 if opt_level == "O2" else None)

        @jax.jit
        def step(state, chunk):
            inputs, labels = chunk[:, :-1], chunk[:, 1:]

            def loss_fn(p):
                cp = state.policy.cast_to_compute(p)
                logits = state.apply_fn(cp, inputs)
                loss = gpt_loss_fn(logits.astype(jnp.float32), labels)
                return state.scale_loss(loss), loss

            grads, loss = jax.grad(loss_fn, has_aux=True)(state.params)
            new_state, _finite = state.apply_gradients(grads=grads)
            return new_state, loss

        losses = []
        for i in range(steps):
            state, loss = step(state, ids[i % n_pool])
            losses.append(float(loss))
        return np.asarray(losses)

    l_o0 = run("O0")
    l_o2 = run("O2")
    assert np.all(np.isfinite(l_o0)) and np.all(np.isfinite(l_o2))

    # (a) both trajectories train: the tail loss must sit well below
    # the head (toy GPT memorizes this stream fast)
    head0, tail0 = l_o0[:10].mean(), l_o0[-20:].mean()
    head2, tail2 = l_o2[:10].mean(), l_o2[-20:].mean()
    assert tail0 < head0 - 1.0, (head0, tail0)
    assert tail2 < head2 - 1.0, (head2, tail2)

    # (b) agreement band: smoothed trajectories track each other to a
    # small fraction of the total training signal.  Window-averaged
    # (single-step losses are noisy under bf16), band = 10% of the
    # O0 head→tail drop, floored at 0.25 nats.
    band = max(0.1 * (head0 - tail0), 0.25)
    k = 20
    smooth0 = np.convolve(l_o0, np.ones(k) / k, mode="valid")
    smooth2 = np.convolve(l_o2, np.ones(k) / k, mode="valid")
    gap = np.abs(smooth0 - smooth2).max()
    assert gap <= band, (
        f"O0/O2 smoothed trajectories diverge by {gap:.3f} nats "
        f"(band {band:.3f}); head/tail O0 {head0:.3f}/{tail0:.3f} "
        f"O2 {head2:.3f}/{tail2:.3f}")
