"""L1-style loss-trajectory artifacts: a few-hundred-step CPU training
run comparing trajectories that must agree within a band.

- **O0 vs O2** (ISSUE-3 satellite / round-5 verdict Missing #5): pure
  fp32 against bf16 compute + fp32 masters + dynamic loss scaling on
  the testing-commons toy GPT — the reference's L1 claim that mixed
  precision changes *arithmetic*, not *optimization*.
- **exact vs int8 AllReduce** (ISSUE-8 satellite, ROADMAP item 2b):
  8-way data-parallel training with the EQuARX-style quantized
  gradient all-reduce (``parallel.ddp.all_reduce_mean_grads(
  allreduce_dtype="int8")``) against the exact fp32 all-reduce — the
  ~amax/127-per-stage quantization noise must not bend the
  optimization trajectory outside the same band.
- **DP vs ZeRO-2** (ISSUE-11 satellite): the same 8-way O2 recipe
  with replicated optimizer state against the ZeRO-2 sharded one
  (``parallel.distributed_optim``: reduce-scatter grads, shard-local
  FusedAdam on fp32 master shards, bf16 param all-gather) — moving
  where the optimizer bytes *live* must not move the trajectory.  The
  ZeRO arm runs under the strict runtime numerics sanitizer
  (``APEX_TPU_NUMCHECK=strict`` semantics): zero violations, and the
  ``apply_gradients.master_shards`` histogram proves the shard-local
  update consumed only fp32 masters.

Both use the same band machinery: same data order, same init,
FusedAdam, 300 steps; the trajectories must (a) both decrease
substantially (the model actually trains) and (b) stay inside an
agreement band wide enough for rounding noise but far tighter than
the training signal itself.

The O0-vs-O2 run additionally rides under the strict runtime numerics
sanitizer (``apex_tpu.utils.numcheck``, ISSUE 10): the O2 leg must
produce zero recorded violations, and the sanitizer's grad
underflow-to-zero fraction plus the ``amp.loss_scale.*`` event
counters are captured beside the trajectories — the correlation hook
that lets a band failure be read against precision events instead of
guessed at.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu import amp
from apex_tpu.models import gpt_loss_fn
from apex_tpu.optim import fused_adam
from apex_tpu.transformer.testing import standalone_gpt
from apex_tpu.utils import numcheck


def _assert_trajectories_agree(l_a, l_b, *, names=("A", "B")):
    """The shared band machinery: both runs train (tail well below
    head) and the smoothed trajectories track each other to a small
    fraction of the training signal (10% of the head→tail drop,
    floored at 0.25 nats — wide enough for bf16/int8 rounding noise,
    far tighter than the ~nats of signal)."""
    assert np.all(np.isfinite(l_a)) and np.all(np.isfinite(l_b))
    head_a, tail_a = l_a[:10].mean(), l_a[-20:].mean()
    head_b, tail_b = l_b[:10].mean(), l_b[-20:].mean()
    assert tail_a < head_a - 1.0, (head_a, tail_a)
    assert tail_b < head_b - 1.0, (head_b, tail_b)

    band = max(0.1 * (head_a - tail_a), 0.25)
    k = 20
    smooth_a = np.convolve(l_a, np.ones(k) / k, mode="valid")
    smooth_b = np.convolve(l_b, np.ones(k) / k, mode="valid")
    gap = np.abs(smooth_a - smooth_b).max()
    assert gap <= band, (
        f"{names[0]}/{names[1]} smoothed trajectories diverge by "
        f"{gap:.3f} nats (band {band:.3f}); head/tail {names[0]} "
        f"{head_a:.3f}/{tail_a:.3f} {names[1]} "
        f"{head_b:.3f}/{tail_b:.3f}")


@pytest.mark.slow
def test_o0_vs_o2_loss_trajectory_agreement():
    steps = 300
    b, s = 8, 32

    model, init_params = standalone_gpt(seed=0, max_seq_len=s)
    vocab = model.cfg.vocab_size
    data_key = jax.random.PRNGKey(1234)
    # a FIXED pool of 4 batches, cycled: fresh random tokens every
    # step would leave nothing learnable (loss pinned at ≈ ln V) —
    # the trajectory signal here is memorization speed
    n_pool = 4
    ids = jax.random.randint(data_key, (n_pool, b, s + 1), 0, vocab,
                             jnp.int32)

    def run(opt_level):
        state = amp.initialize(
            model.apply, {"params": init_params},
            fused_adam(3e-4),
            opt_level=opt_level,
            half_dtype=jnp.bfloat16 if opt_level == "O2" else None)

        @jax.jit
        def step(state, chunk):
            inputs, labels = chunk[:, :-1], chunk[:, 1:]

            def loss_fn(p):
                cp = state.policy.cast_to_compute(p)
                logits = state.apply_fn(cp, inputs)
                loss = gpt_loss_fn(logits.astype(jnp.float32), labels)
                return state.scale_loss(loss), loss

            grads, loss = jax.grad(loss_fn, has_aux=True)(state.params)
            new_state, _finite = state.apply_gradients(grads=grads)
            return new_state, loss

        losses = []
        for i in range(steps):
            state, loss = step(state, ids[i % n_pool])
            losses.append(float(loss))
        return np.asarray(losses)

    l_o0 = run("O0")
    # the O2 leg runs under the strict numerics sanitizer: zero
    # violations, and its precision events are captured so a band
    # failure can be correlated with underflow / scale-backoff bursts
    numcheck.reset()
    numcheck.instrument(strict=True)
    try:
        l_o2 = run("O2")
        jax.effects_barrier()
        numcheck.assert_clean()
        stats = numcheck.summary()
        assert stats["grad_stat_steps"] == steps
        # bf16 O2 carries no loss scaling; the counters still exist
        # (zeros here) — the fp16 chaos smoke proves the nonzero path
        assert stats["loss_scale_backoff"] >= 0
        context = (f"numcheck: underflow_frac="
                   f"{stats['grad_underflow_frac']:.4f} "
                   f"backoff={stats['loss_scale_backoff']} "
                   f"growth={stats['loss_scale_growth']}")
    finally:
        numcheck.uninstrument()
        numcheck.reset()

    print(context)      # lands in the failure report via pytest -rA
    _assert_trajectories_agree(l_o0, l_o2, names=("O0", "O2"))


@pytest.mark.slow
def test_exact_vs_int8_allreduce_loss_trajectory_agreement():
    """ROADMAP 2b acceptance: the int8 (EQuARX-style) gradient
    all-reduce A/B'd for loss-trajectory agreement.  8-way DP on the
    virtual CPU mesh, global batch and data order IDENTICAL between
    runs — the only difference is the wire dtype of the grad sync."""
    import optax

    from apex_tpu import parallel as apx_parallel
    from jax.sharding import PartitionSpec as P

    steps = 300
    b, s = 16, 32                    # 2 rows per shard on 8 devices

    model, init_params = standalone_gpt(seed=0, max_seq_len=s)
    vocab = model.cfg.vocab_size
    n_pool = 4
    ids = jax.random.randint(jax.random.PRNGKey(1234),
                             (n_pool, b, s + 1), 0, vocab, jnp.int32)
    # a RAW jax mesh, deliberately NOT registered with core.mesh: the
    # whole step runs fully-manual inside shard_map, so the model's
    # maybe_constrain annotations must degrade to no-ops (they would
    # error on manual axes if the library-global mesh were set)
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:8]), ("data",))

    def run(allreduce_dtype):
        tx = fused_adam(3e-4)
        params = jax.tree.map(jnp.asarray, init_params)
        opt_state = tx.init(params)

        def dp_step(p, st, chunk):
            inputs, labels = chunk[:, :-1], chunk[:, 1:]

            def loss_fn(p):
                logits = model.apply({"params": p}, inputs)
                return gpt_loss_fn(logits.astype(jnp.float32),
                                   labels)

            loss, grads = jax.value_and_grad(loss_fn)(p)
            grads = apx_parallel.all_reduce_mean_grads(
                grads, "data", allreduce_dtype=allreduce_dtype)
            loss = jax.lax.pmean(loss, "data")
            updates, st2 = tx.update(grads, st, p)
            return optax.apply_updates(p, updates), st2, loss

        step = jax.jit(jax.shard_map(
            dp_step, mesh=mesh,
            in_specs=(P(), P(), P("data")),
            out_specs=(P(), P(), P()), check_vma=False))

        losses = []
        for i in range(steps):
            params, opt_state, loss = step(params, opt_state,
                                           ids[i % n_pool])
            losses.append(float(loss))
        return np.asarray(losses)

    _assert_trajectories_agree(run(None), run("int8"),
                               names=("fp32", "int8"))


@pytest.mark.slow
def test_dp_vs_zero2_loss_trajectory_agreement():
    """ISSUE-11 acceptance leg: exact-DP vs ZeRO-2 on the
    testing-commons GPT under O2/bf16 — same band machinery as the
    legs above; the only difference is where the optimizer state
    lives and how the grads sync (all-reduce of full grads vs
    reduce-scatter into fp32 master shards + bf16 param all-gather).
    The ZeRO arm runs under the strict numerics sanitizer."""
    from apex_tpu import parallel as apx_parallel
    from apex_tpu.parallel import ZeroConfig, zero_state_specs
    from jax.sharding import PartitionSpec as P

    steps = 300
    b, s = 16, 32                    # 2 rows per shard on 8 devices

    model, init_params = standalone_gpt(seed=0, max_seq_len=s)
    vocab = model.cfg.vocab_size
    n_pool = 4
    ids = jax.random.randint(jax.random.PRNGKey(1234),
                             (n_pool, b, s + 1), 0, vocab, jnp.int32)
    # raw mesh, NOT registered with core.mesh (see the int8 leg above)
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:8]), ("data",))

    def losses_of(step, state):
        out = []
        for i in range(steps):
            state, loss = step(state, ids[i % n_pool])
            out.append(float(loss))
        return np.asarray(out)

    def run_dp():
        state = amp.initialize(
            model.apply, {"params": init_params}, fused_adam(3e-4),
            opt_level="O2", half_dtype=jnp.bfloat16)

        def dp_step(state, chunk):
            inputs, labels = chunk[:, :-1], chunk[:, 1:]

            def loss_fn(p):
                cp = state.policy.cast_to_compute(p)
                logits = state.apply_fn(cp, inputs)
                loss = gpt_loss_fn(logits.astype(jnp.float32), labels)
                return state.scale_loss(loss), loss

            grads, loss = jax.grad(loss_fn, has_aux=True)(state.params)
            grads = apx_parallel.all_reduce_mean_grads(grads, "data")
            new_state, _ = state.apply_gradients(grads=grads)
            return new_state, jax.lax.pmean(loss, "data")

        step = jax.jit(jax.shard_map(
            dp_step, mesh=mesh,
            in_specs=(P(), P("data")), out_specs=(P(), P()),
            check_vma=False))
        return losses_of(step, state)

    def run_zero2():
        state = amp.initialize(
            model.apply, {"params": init_params}, fused_adam(3e-4),
            opt_level="O2", half_dtype=jnp.bfloat16,
            zero=ZeroConfig(axis="data", stage=2, axis_size=8))
        specs = zero_state_specs(state)

        def z_step(state, chunk):
            inputs, labels = chunk[:, :-1], chunk[:, 1:]

            def loss_fn(p):
                cp = state.policy.cast_to_compute(p)
                logits = state.apply_fn(cp, inputs)
                loss = gpt_loss_fn(logits.astype(jnp.float32), labels)
                return state.scale_loss(loss), loss

            grads, loss = jax.grad(loss_fn, has_aux=True)(state.params)
            # per-replica grads: apply_gradients owns the sync
            new_state, _ = state.apply_gradients(grads=grads)
            return new_state, jax.lax.pmean(loss, "data")

        step = jax.jit(jax.shard_map(
            z_step, mesh=mesh,
            in_specs=(specs, P("data")), out_specs=(specs, P()),
            check_vma=False))
        return losses_of(step, state)

    l_dp = run_dp()
    numcheck.reset()
    numcheck.instrument(strict=True)
    try:
        l_zero = run_zero2()
        jax.effects_barrier()
        numcheck.assert_clean()
        hist = numcheck.site_histograms()
        # fp32 master shards verified at runtime
        assert set(hist["apply_gradients.master_shards"]) == \
            {"float32"}, hist
        stats = numcheck.summary()
        assert stats["grad_stat_steps"] > 0
        context = (f"numcheck[zero2]: underflow_frac="
                   f"{stats['grad_underflow_frac']:.4f} "
                   f"violations={stats['violations']}")
    finally:
        numcheck.uninstrument()
        numcheck.reset()

    print(context)      # lands in the failure report via pytest -rA
    _assert_trajectories_agree(l_dp, l_zero, names=("DP", "ZeRO-2"))


@pytest.mark.slow
def test_dp_vs_dp_pipe_loss_trajectory_agreement():
    """ISSUE-20 acceptance leg: pure 8-way DP against the composed
    dp=2 × pipe=4 1F1B step (stage-local ZeRO-2) at equal chips, same
    global batch, same optimizer — pipelining reorders the *schedule*
    of the microbatch forwards/backwards, not the gradient they sum
    to, so the trajectories must sit inside the same band the other
    legs use."""
    import optax

    from apex_tpu.optim import fused_adam as _fa
    from apex_tpu.parallel import ZeroConfig
    from apex_tpu.parallel import pipeline as pl
    from jax.sharding import Mesh, PartitionSpec as P

    steps = 300
    hid, dp, pp, m, mb = 16, 2, 4, 8, 2      # 32 global samples
    layers = 4                               # 1 layer per stage

    r = np.random.default_rng(0)
    init = {"stages": (
        jnp.asarray(r.normal(size=(layers, hid, hid)) * 0.3,
                    jnp.float32),
        jnp.asarray(r.normal(size=(layers, hid)) * 0.1, jnp.float32),
        jnp.asarray(r.normal(size=(layers, hid, hid)) * 0.3,
                    jnp.float32),
    )}
    # fixed pool of 4 batches, cycled — the signal is memorization
    # speed, exactly like the GPT legs above
    n_pool = 4
    xs = jnp.asarray(r.normal(size=(n_pool, dp * m, mb, hid)),
                     jnp.float32)
    ys = jnp.asarray(r.normal(size=(n_pool, dp * m, mb, hid)),
                     jnp.float32)

    def layer_apply(x, args):
        w1, b1, w2 = args
        h = jnp.tanh(x @ w1 + b1)
        return x + h @ w2, None

    def stage_fn(params, x):
        x, _ = jax.lax.scan(layer_apply, x, params)
        return x

    def run_dp():
        mesh = jax.sharding.Mesh(np.array(jax.devices()[:8]),
                                 ("data",))
        tx = _fa(1e-2)
        params = init
        opt_state = tx.init(params)

        def dp_step(p, st, x, y):
            def loss_fn(p):
                out, _ = jax.lax.scan(layer_apply, x, p["stages"])
                return jnp.mean((out - y) ** 2)

            loss, grads = jax.value_and_grad(loss_fn)(p)
            grads = jax.tree.map(
                lambda g: jax.lax.pmean(g, "data"), grads)
            loss = jax.lax.pmean(loss, "data")
            updates, st2 = tx.update(grads, st, p)
            return optax.apply_updates(p, updates), st2, loss

        step = jax.jit(jax.shard_map(
            dp_step, mesh=mesh,
            in_specs=(P(), P(), P("data"), P("data")),
            out_specs=(P(), P(), P()), check_vma=False))
        losses = []
        for i in range(steps):
            j = i % n_pool
            x = xs[j].reshape(-1, hid)
            y = ys[j].reshape(-1, hid)
            params, opt_state, loss = step(params, opt_state, x, y)
            losses.append(float(loss))
        return np.asarray(losses)

    def run_dp_pipe():
        from apex_tpu import amp as _amp

        mesh = Mesh(np.array(jax.devices()[:dp * pp]).reshape(dp, pp),
                    ("data", "pipe"))
        staged = {"stages": pl.stage_split(init["stages"], pp)}
        state = _amp.initialize(
            None, staged, _fa(1e-2), opt_level="O0",
            zero=ZeroConfig(axis="data", axis_size=dp, stage=2))
        state = pl.stage_local_zero(state, num_stages=pp)
        state = jax.device_put(
            state, pl.pipeline_state_shardings(state, mesh=mesh))

        def body(state, mbs, labels):
            def loss_fn(out, i):
                yl = jax.lax.dynamic_index_in_dim(labels, i, 0,
                                                  keepdims=False)
                return jnp.mean((out - yl) ** 2)

            loss, grads = pl.run_1f1b(stage_fn, loss_fn,
                                      state.params["stages"], mbs)
            grads = pl.sync_grad_overflow({"stages": grads})
            new_state, _ = state.apply_gradients(grads=grads)
            return new_state, jax.lax.pmean(loss, "data")

        step = pl.wrap_pipeline_step(
            body, state=state, mesh=mesh,
            batch_specs=(P("data"), P("data")))
        losses = []
        for i in range(steps):
            j = i % n_pool
            state, loss = step(state, xs[j], ys[j])
            losses.append(float(loss))
        return np.asarray(losses)

    _assert_trajectories_agree(run_dp(), run_dp_pipe(),
                               names=("DP", "DPxPIPE"))
