"""Golden tests for softmax / RoPE / xentropy / MLP / GroupNorm ops —
reference pattern: fused (Pallas-interpret) vs eager composition vs
torch, fwd and bwd (SURVEY.md §4, ``tests/L0/run_transformer`` style)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from apex_tpu import ops

# L0 fast tier: golden kernel/state-machine tests (pytest -m l0)
pytestmark = pytest.mark.l0


def _x(rng, shape, dtype=jnp.float32):
    return jnp.asarray(rng.normal(size=shape), dtype)


SK = 256  # lane-aligned key length


class TestScaleMaskSoftmax:
    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    def test_plain(self, rng, dtype):
        x = _x(rng, (2, 4, 8, SK), dtype)
        got = ops.fused_scale_mask_softmax(
            x, scale=0.5, implementation="pallas_interpret")
        want = ops.scale_mask_softmax_reference(x, scale=0.5)
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(want, np.float32),
            rtol=2e-2 if dtype == jnp.bfloat16 else 1e-5, atol=1e-5)

    def test_boolean_mask(self, rng):
        x = _x(rng, (2, 2, 4, SK))
        mask = jnp.asarray(rng.random((2, 1, 4, SK)) > 0.7)
        got = ops.fused_scale_mask_softmax(
            x, mask, scale=2.0, implementation="pallas_interpret")
        want = ops.scale_mask_softmax_reference(x, mask, scale=2.0)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)

    def test_causal_in_kernel(self, rng):
        x = _x(rng, (2, 2, SK, SK))
        got = ops.fused_scale_mask_softmax(
            x, causal=True, scale=0.125,
            implementation="pallas_interpret")
        want = ops.scale_mask_softmax_reference(x, causal=True, scale=0.125)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)
        # strictly-upper-triangular must be exactly ~0
        up = np.triu(np.ones((SK, SK), bool), k=1)
        assert np.all(np.asarray(got)[..., up] < 1e-6)

    def test_causal_rectangular(self, rng):
        # sq != sk: causal offset (sk - sq) like the reference generic kernel
        x = _x(rng, (1, 1, 64, SK))
        got = ops.fused_scale_mask_softmax(
            x, causal=True, implementation="pallas_interpret")
        want = ops.scale_mask_softmax_reference(x, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)

    def test_backward_vs_reference_autodiff(self, rng):
        x = _x(rng, (2, 2, 8, SK))

        def f_fused(x):
            y = ops.fused_scale_mask_softmax(
                x, scale=0.7, causal=True,
                implementation="pallas_interpret")
            return jnp.sum(y * y)

        def f_ref(x):
            y = ops.scale_mask_softmax_reference(x, scale=0.7, causal=True)
            return jnp.sum(y * y)

        np.testing.assert_allclose(
            np.asarray(jax.grad(f_fused)(x)),
            np.asarray(jax.grad(f_ref)(x)), rtol=1e-4, atol=1e-6)

    def test_vs_torch_softmax(self, rng):
        x_np = rng.normal(size=(3, SK)).astype(np.float32)
        got = ops.fused_scale_mask_softmax(
            jnp.asarray(x_np), scale=1.0,
            implementation="pallas_interpret")
        want = torch.softmax(torch.tensor(x_np), dim=-1)
        np.testing.assert_allclose(np.asarray(got), want.numpy(),
                                   rtol=1e-5, atol=1e-6)


class TestRope:
    def test_fused_vs_reference(self, rng):
        b, s, h, d = 2, 16, 4, 128
        x = _x(rng, (b, s, h, d))
        cos, sin = ops.rope_cos_sin(s, d)
        got = ops.fused_rope(x, cos, sin,
                             implementation="pallas_interpret")
        want = ops.rope_reference(x, cos, sin)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    def test_sbhd_layout(self, rng):
        s, h, d = 12, 2, 128
        x = _x(rng, (s, h, d))
        cos, sin = ops.rope_cos_sin(s, d)
        got = ops.fused_rope(x, cos, sin,
                             implementation="pallas_interpret")
        want = ops.rope_reference(x, cos, sin)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    def test_partial_rotary_fallback(self, rng):
        # rot_dim < head_dim → tail passthrough (XLA path; 64 unaligned)
        b, s, h, d = 1, 8, 2, 128
        x = _x(rng, (b, s, h, d))
        cos, sin = ops.rope_cos_sin(s, 64)
        got = ops.fused_rope(x, cos, sin, implementation="xla")
        np.testing.assert_allclose(np.asarray(got[..., 64:]),
                                   np.asarray(x[..., 64:]))

    def test_backward_rotation_transpose(self, rng):
        b, s, h, d = 1, 8, 2, 128
        x = _x(rng, (b, s, h, d))
        cos, sin = ops.rope_cos_sin(s, d)

        def f_fused(x):
            return jnp.sum(jnp.cos(ops.fused_rope(
                x, cos, sin, implementation="pallas_interpret")))

        def f_ref(x):
            return jnp.sum(jnp.cos(ops.rope_reference(x, cos, sin)))

        np.testing.assert_allclose(
            np.asarray(jax.grad(f_fused)(x)),
            np.asarray(jax.grad(f_ref)(x)), rtol=1e-4, atol=1e-5)

    def test_norm_preserved(self, rng):
        # rotation preserves per-pair norms
        b, s, h, d = 1, 4, 1, 128
        x = _x(rng, (b, s, h, d))
        cos, sin = ops.rope_cos_sin(s, d)
        y = ops.fused_rope(x, cos, sin, implementation="pallas_interpret")
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(y), axis=-1),
            np.linalg.norm(np.asarray(x), axis=-1), rtol=1e-5)


class TestXentropy:
    @pytest.mark.parametrize("smoothing", [0.0, 0.1])
    def test_vs_torch(self, rng, smoothing):
        n, v = 16, 1000
        logits_np = rng.normal(size=(n, v)).astype(np.float32) * 3
        labels_np = rng.integers(0, v, size=(n,))
        got = ops.softmax_cross_entropy(
            jnp.asarray(logits_np), jnp.asarray(labels_np), smoothing)
        want = torch.nn.functional.cross_entropy(
            torch.tensor(logits_np), torch.tensor(labels_np),
            label_smoothing=smoothing, reduction="none")
        np.testing.assert_allclose(np.asarray(got), want.numpy(),
                                   rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("smoothing", [0.0, 0.2])
    def test_grads_vs_torch(self, rng, smoothing):
        n, v = 8, 257
        logits_np = rng.normal(size=(n, v)).astype(np.float32)
        labels_np = rng.integers(0, v, size=(n,))

        def f(l):
            return jnp.mean(ops.softmax_cross_entropy(
                l, jnp.asarray(labels_np), smoothing))

        dl = jax.grad(f)(jnp.asarray(logits_np))
        lt = torch.tensor(logits_np, requires_grad=True)
        torch.nn.functional.cross_entropy(
            lt, torch.tensor(labels_np),
            label_smoothing=smoothing).backward()
        np.testing.assert_allclose(np.asarray(dl), lt.grad.numpy(),
                                   rtol=1e-4, atol=1e-6)

    def test_ignore_index(self, rng):
        n, v = 6, 50
        logits = _x(rng, (n, v))
        labels = jnp.asarray([1, 2, 0, 0, 3, 4])
        loss = ops.softmax_cross_entropy(logits, labels, 0.0, 0)
        assert float(loss[2]) == 0.0 and float(loss[3]) == 0.0
        # grads of ignored rows are zero
        g = jax.grad(lambda l: jnp.sum(
            ops.softmax_cross_entropy(l, labels, 0.0, 0)))(logits)
        np.testing.assert_array_equal(np.asarray(g[2]), 0.0)

    def test_half_input_fp32_loss(self, rng):
        logits = _x(rng, (4, 128), jnp.bfloat16)
        labels = jnp.asarray([0, 1, 2, 3])
        loss = ops.softmax_cross_entropy(logits, labels)
        assert loss.dtype == jnp.float32  # half_to_float parity


class TestMLP:
    def test_fused_dense_vs_torch_linear(self, rng):
        x_np = rng.normal(size=(4, 32)).astype(np.float32)
        w_np = rng.normal(size=(32, 16)).astype(np.float32)
        b_np = rng.normal(size=(16,)).astype(np.float32)
        # pin true-fp32 matmul: TPU's DEFAULT precision runs bf16 passes
        # (~1e-2 error), which is hardware behavior, not op math
        with jax.default_matmul_precision("highest"):
            got = ops.fused_dense(jnp.asarray(x_np), jnp.asarray(w_np),
                                  jnp.asarray(b_np))
        want = torch.nn.functional.linear(
            torch.tensor(x_np), torch.tensor(w_np).T, torch.tensor(b_np))
        np.testing.assert_allclose(np.asarray(got), want.numpy(),
                                   rtol=1e-5, atol=1e-5)

    def test_mlp_module_matches_reference_semantics(self, rng):
        # activation on all but last layer, like apex.mlp.MLP
        m = ops.MLP(mlp_sizes=(64, 32, 8), activation="relu")
        x = _x(rng, (4, 16))
        params = m.init(jax.random.PRNGKey(0), x)
        y = m.apply(params, x)
        assert y.shape == (4, 8)
        # last layer linear → output can be negative
        assert float(jnp.min(y)) < 0

    def test_dense_gelu_dense(self, rng):
        m = ops.FusedDenseGeluDense(intermediate_features=64,
                                    out_features=16)
        x = _x(rng, (4, 16))
        params = m.init(jax.random.PRNGKey(0), x)
        y = m.apply(params, x)
        assert y.shape == (4, 16)

    def test_bf16_compute_fp32_accumulate(self, rng):
        x = _x(rng, (8, 128), jnp.bfloat16)
        w = _x(rng, (128, 64), jnp.bfloat16)
        y = ops.fused_dense(x, w)
        assert y.dtype == jnp.bfloat16


class TestGroupNorm:
    def test_vs_torch(self, rng):
        n, hh, ww, c = 2, 4, 4, 32
        x_np = rng.normal(size=(n, hh, ww, c)).astype(np.float32)
        w_np = rng.normal(size=(c,)).astype(np.float32)
        b_np = rng.normal(size=(c,)).astype(np.float32)
        got = ops.group_norm(jnp.asarray(x_np), 8, jnp.asarray(w_np),
                             jnp.asarray(b_np))
        # torch is NCHW
        want = torch.nn.functional.group_norm(
            torch.tensor(x_np).permute(0, 3, 1, 2), 8,
            torch.tensor(w_np), torch.tensor(b_np)
        ).permute(0, 2, 3, 1)
        np.testing.assert_allclose(np.asarray(got), want.numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_silu_fusion(self, rng):
        x = _x(rng, (2, 4, 4, 16))
        y = ops.group_norm(x, 4, act="silu")
        base = ops.group_norm(x, 4)
        np.testing.assert_allclose(
            np.asarray(y),
            np.asarray(base) * (1 / (1 + np.exp(-np.asarray(base)))),
            rtol=1e-5, atol=1e-6)

    def test_module(self, rng):
        m = ops.GroupNorm(num_groups=4, act="silu")
        x = _x(rng, (2, 3, 3, 16))
        params = m.init(jax.random.PRNGKey(0), x)
        y = m.apply(params, x)
        assert y.shape == x.shape

    def test_bad_groups_raises(self, rng):
        with pytest.raises(ValueError):
            ops.group_norm(_x(rng, (1, 2, 2, 10)), 3)

    @pytest.mark.parametrize("act", [None, "silu"])
    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    def test_pallas_kernel_matches_reference(self, rng, act, dtype):
        """Round-3 Pallas GN fwd+bwd vs the XLA golden (the round-2
        composition): values and all three grads."""
        from apex_tpu.ops.group_norm import group_norm_reference

        n, hh, ww, c, g = 2, 8, 8, 256, 8
        x = jnp.asarray(rng.normal(size=(n, hh, ww, c)), dtype)
        w = jnp.asarray(rng.normal(size=(c,)) * 0.5 + 1.0, jnp.float32)
        b = jnp.asarray(rng.normal(size=(c,)) * 0.1, jnp.float32)
        bf16 = dtype == jnp.bfloat16
        rtol, atol = (3e-2, 3e-2) if bf16 else (2e-5, 1e-5)

        got = ops.group_norm(x, g, w, b, act=act,
                             implementation="pallas_interpret")
        want = group_norm_reference(x, g, w, b, act=act)
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   np.asarray(want, np.float32),
                                   rtol=rtol, atol=atol)

        def grads(fn):
            def f(x, w, b):
                return jnp.sum(fn(x, w, b).astype(jnp.float32) ** 2)
            return jax.grad(f, argnums=(0, 1, 2))(x, w, b)

        gp = grads(lambda x, w, b: ops.group_norm(
            x, g, w, b, act=act, implementation="pallas_interpret"))
        gr = grads(lambda x, w, b: group_norm_reference(
            x, g, w, b, act=act))
        rtol, atol = (4e-2, 4e-2) if bf16 else (5e-5, 1e-4)
        for a, bb in zip(gp, gr):
            np.testing.assert_allclose(np.asarray(a, np.float32),
                                       np.asarray(bb, np.float32),
                                       rtol=rtol, atol=atol)

    def test_odd_spatial_falls_back(self, rng):
        # (3, 3) spatial: no 8-aligned divisor -> XLA path; still exact
        from apex_tpu.ops.group_norm import group_norm_reference

        x = _x(rng, (2, 3, 3, 128))
        got = ops.group_norm(x, 4)
        want = group_norm_reference(x, 4)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)


class TestAutotune:
    """Sweep-and-cache block-size autotuner (round-1 verdict weak 7:
    the 'autotuned' claim must be backed by a real measured table)."""

    def test_cache_roundtrip_and_precedence(self, tmp_path, monkeypatch):
        from apex_tpu.ops import autotune
        from apex_tpu.ops._dispatch import pick_block_rows

        monkeypatch.setenv("APEX_TPU_AUTOTUNE_CACHE",
                           str(tmp_path / "autotune.json"))
        autotune.clear_cache()
        try:
            # no entry: heuristic answer
            base = pick_block_rows(4096, 1024, op="layer_norm",
                                   dtype="bfloat16")
            assert base % 8 == 0
            # store a measured entry; it must take precedence
            autotune._store(autotune._key("layer_norm", 1024, "bfloat16"),
                            64)
            assert pick_block_rows(4096, 1024, op="layer_norm",
                                   dtype="bfloat16") == 64
            # different width misses the cache: heuristic answer
            assert pick_block_rows(4096, 2048, op="layer_norm",
                                   dtype="bfloat16") == pick_block_rows(
                                       4096, 2048)
            assert pick_block_rows(4096, 1024, op="softmax",
                                   dtype="bfloat16") == base
            # clamped to the row count
            autotune._store(autotune._key("softmax", 512, "float32"),
                            4096)
            assert pick_block_rows(16, 512, op="softmax",
                                   dtype="float32") == 16
            # persisted: a fresh in-memory cache reloads from disk
            autotune.clear_cache()
            assert autotune.cached_block_rows(
                "layer_norm", 1024, "bfloat16") == 64
        finally:
            autotune.clear_cache()

    def test_tune_layer_norm_interpret_path(self, tmp_path, monkeypatch):
        """The sweep machinery runs end-to-end (interpret kernels are
        not worth timing, but the plumbing must not crash and must
        write a winner on a backend where candidates execute)."""
        from apex_tpu.ops import autotune

        monkeypatch.setenv("APEX_TPU_AUTOTUNE_CACHE",
                           str(tmp_path / "t.json"))
        autotune.clear_cache()
        try:
            best = autotune._tune(
                "noop", lambda br: (lambda x: x, (jax.numpy.ones((8, 8)),)),
                n_rows=64, width=8, dtype="float32", candidates=(8, 16))
            assert best in (8, 16)
            assert autotune.cached_block_rows("noop", 8, "float32") == best
        finally:
            autotune.clear_cache()
