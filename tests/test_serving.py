"""Continuous-batching serving engine (apex_tpu.serving).

Correctness contracts under test:
- greedy decode through the slotted engine is TOKEN-IDENTICAL to the
  fixed-batch ``generate()`` loop for the same prompts;
- a steady-state soak interleaving admissions/evictions across >= 3
  prompt-length buckets with heterogeneous sampling params triggers
  ZERO retraces after warmup (asserted both via the process-wide
  trace-event counter and the engine's own ``retrace_guard`` budgets,
  which would raise ``RetraceError`` on any excess trace);
- a request's sampled tokens depend on its own seed, not on its
  co-tenants (per-slot rng);
- the threaded ``InferenceServer`` streams tokens, emits metrics, and
  shuts down cleanly.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu.models import GPTConfig, GPTModel, LlamaConfig, LlamaModel, generate
from apex_tpu.serving import (
    Engine,
    InferenceServer,
    QueueFull,
    Request,
    Scheduler,
)
from apex_tpu.serving import cache as slot_cache
from apex_tpu.utils import MetricsWriter, tracecheck
from apex_tpu.utils.tracecheck import RetraceError


def _tiny_gpt():
    cfg = GPTConfig.tiny(position_embedding="learned",
                         scan_layers=True)
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))
    return model, {"params": params["params"]}


def _tiny_llama():
    cfg = LlamaConfig.tiny(scan_layers=True)
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))
    return model, {"params": params["params"]}


@pytest.fixture(scope="module")
def gpt():
    return _tiny_gpt()


@pytest.fixture(scope="module")
def llama():
    return _tiny_llama()


def _prompts(rng, vocab, lengths):
    return [rng.integers(0, vocab, size=(L,)).astype(np.int32)
            for L in lengths]


class TestSlotCache:
    def test_pool_shapes_and_reset(self, gpt):
        model, _ = gpt
        from apex_tpu.models.generate import cache_shapes

        shapes = cache_shapes(model, 1)
        pool = slot_cache.stacked_zeros(shapes, 3)
        flat = jax.tree.leaves(pool)
        per_slot = jax.tree.leaves(shapes)
        assert all(p.shape == (3,) + tuple(s.shape)
                   for p, s in zip(flat, per_slot))
        # write then reset roundtrips to zeros
        one = jax.tree.map(
            lambda s: jnp.ones(s.shape, s.dtype), shapes)
        pool = slot_cache.write_slot(pool, 1, one)
        assert all(float(jnp.sum(jnp.abs(leaf[1].astype(jnp.float32))))
                   > 0 for leaf in jax.tree.leaves(pool))
        pool = slot_cache.reset_slot(pool, 1)
        assert all(float(jnp.sum(jnp.abs(leaf.astype(jnp.float32))))
                   == 0 for leaf in jax.tree.leaves(pool))

    def test_rewind_targets_only_index_leaves(self, gpt):
        model, _ = gpt
        from apex_tpu.models.generate import init_cache

        cache = init_cache(model, 1)
        cache = jax.tree.map(
            lambda x: x + jnp.ones_like(x), cache)
        out = slot_cache.rewind_index_leaves(cache, 7)
        flat = jax.tree_util.tree_flatten_with_path(out)[0]
        saw_index = 0
        for path, leaf in flat:
            name = slot_cache._leaf_name(path)
            if name in ("cache_index", "position_index"):
                saw_index += 1
                assert np.all(np.asarray(leaf) == 7), name
            else:
                assert np.all(np.asarray(leaf) == 1), name
        assert saw_index >= 2       # per-layer cache_index + model pos

    def test_sliding_window_cache_rejected(self):
        cfg = LlamaConfig.tiny(sliding_window=5, scan_layers=False)
        model = LlamaModel(cfg)
        params = model.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, 4), jnp.int32))
        with pytest.raises(ValueError, match="ring-buffer"):
            Engine(model, {"params": params["params"]},
                   max_slots=2, prompt_buckets=(8,))


class TestEngineValidation:
    def test_bucket_exceeding_max_seq_len_rejected(self, gpt):
        model, params = gpt
        S = model.cfg.max_seq_len
        with pytest.raises(ValueError, match="bucket"):
            Engine(model, params, prompt_buckets=(S,))

    def test_oversized_request_rejected_at_submit(self, gpt):
        model, params = gpt
        engine = Engine(model, params, max_slots=1,
                        prompt_buckets=(8,))
        sched = Scheduler(engine)
        with pytest.raises(ValueError, match="bucket"):
            sched.submit(Request(prompt=np.zeros(9, np.int32),
                                 max_new_tokens=1))
        with pytest.raises(ValueError, match="max_seq_len"):
            sched.submit(Request(
                prompt=np.zeros(8, np.int32),
                max_new_tokens=model.cfg.max_seq_len))
        with pytest.raises(ValueError, match="top_k"):
            sched.submit(Request(prompt=np.zeros(4, np.int32),
                                 max_new_tokens=2,
                                 temperature=1.0,
                                 top_k=model.cfg.vocab_size + 1))

    def test_queue_capacity_bounded(self, gpt):
        model, params = gpt
        engine = Engine(model, params, max_slots=1,
                        prompt_buckets=(8,))
        sched = Scheduler(engine, queue_capacity=2)
        for _ in range(2):
            sched.submit(Request(prompt=np.zeros(4, np.int32),
                                 max_new_tokens=1))
        with pytest.raises(QueueFull):
            sched.submit(Request(prompt=np.zeros(4, np.int32),
                                 max_new_tokens=1))


class TestGreedyParity:
    # [the llama twin is slow-marked: ~17s of CPU compile for the same
    # dense-engine property the gpt twin pins in tier-1; it still runs
    # under -m slow and in the on-chip pass]
    @pytest.mark.l0
    @pytest.mark.parametrize("which", [
        "gpt", pytest.param("llama", marks=pytest.mark.slow)])
    def test_engine_matches_generate(self, which, request):
        """Mixed-length greedy requests through 2 slots must reproduce
        generate()'s token chains exactly — including requests that
        queue behind the first wave (continuous refill)."""
        model, params = request.getfixturevalue(which)
        rng = np.random.default_rng(3)
        prompts = _prompts(rng, model.cfg.vocab_size,
                           (3, 5, 8, 4, 11))
        budgets = [6, 3, 5, 7, 4]
        engine = Engine(model, params, max_slots=2,
                        prompt_buckets=(4, 8, 16))
        sched = Scheduler(engine)
        reqs = [sched.submit(Request(prompt=p, max_new_tokens=n))
                for p, n in zip(prompts, budgets)]
        sched.drain()
        for p, n, r in zip(prompts, budgets, reqs):
            ref = np.asarray(generate(
                model, params, jnp.asarray(p[None]),
                max_new_tokens=n))[0, len(p):]
            np.testing.assert_array_equal(
                np.asarray(r.tokens), ref,
                err_msg=f"{which} prompt_len={len(p)} n={n}")

    def test_chunked_prefill_engine_matches_generate(self, gpt):
        """The engine's prefill rides the same chunked path as
        generate(prefill_chunk=...): forcing small chunks must not
        change the greedy token chain."""
        model, params = gpt
        rng = np.random.default_rng(19)
        prompt = rng.integers(0, model.cfg.vocab_size,
                              size=(11,)).astype(np.int32)
        ref = np.asarray(generate(
            model, params, jnp.asarray(prompt[None]),
            max_new_tokens=4))[0, 11:]
        engine = Engine(model, params, max_slots=1,
                        prompt_buckets=(16,), prefill_chunk=4)
        sched = Scheduler(engine)
        req = sched.submit(Request(prompt=prompt, max_new_tokens=4))
        sched.drain()
        np.testing.assert_array_equal(np.asarray(req.tokens), ref)

    def test_eos_stops_early_and_matches_generate(self, gpt):
        model, params = gpt
        rng = np.random.default_rng(5)
        prompt = rng.integers(0, model.cfg.vocab_size,
                              size=(5,)).astype(np.int32)
        n = 8
        ref = np.asarray(generate(
            model, params, jnp.asarray(prompt[None]),
            max_new_tokens=n))[0, 5:]
        eos = int(ref[2])            # force a stop three tokens in
        engine = Engine(model, params, max_slots=1,
                        prompt_buckets=(8,))
        sched = Scheduler(engine)
        req = sched.submit(Request(prompt=prompt, max_new_tokens=n,
                                   eos_id=eos))
        sched.drain()
        got = np.asarray(req.tokens)
        # engine stops AT the produced eos; generate's chain up to the
        # first eos must match token for token
        first = int(np.argmax(ref == eos))
        np.testing.assert_array_equal(got, ref[:first + 1])
        assert got[-1] == eos and len(got) < n


class TestTopPSampling:
    @pytest.mark.slow
    def test_top_p_one_matches_disabled(self, gpt):
        # [slow: two engine builds ≈ 8 s; the fast tier covers the
        # exact-no-op contract at the sample_dynamic level below and
        # mixes top_p=1.0 traffic through the zero-retrace soak]
        """top_p=1.0 and top_p=None are the same program AND the same
        tokens (the disabled nucleus filter is an exact no-op in
        sample_dynamic, not an epsilon approximation)."""
        model, params = gpt
        rng = np.random.default_rng(23)
        prompt = rng.integers(0, model.cfg.vocab_size,
                              size=(6,)).astype(np.int32)

        def run(top_p):
            engine = Engine(model, params, max_slots=1,
                            prompt_buckets=(8,))
            sched = Scheduler(engine)
            req = sched.submit(Request(
                prompt=prompt, max_new_tokens=6, temperature=0.9,
                top_p=top_p, seed=5))
            sched.drain()
            return list(req.tokens)

        assert run(None) == run(1.0)

    def test_dynamic_nucleus_restricts_tokens(self, gpt):
        """sample_dynamic with a per-slot top_p must only emit tokens
        from each row's nucleus; disabled rows are exact no-ops."""
        from apex_tpu.serving.engine import sample_dynamic

        rng = np.random.default_rng(3)
        V = 32
        logits = jnp.asarray(rng.normal(size=(2, V)) * 3.0,
                             jnp.float32)
        temp = jnp.asarray([0.8, 0.8], jnp.float32)
        top_k = jnp.zeros((2,), jnp.int32)
        top_p = jnp.asarray([0.6, 0.0], jnp.float32)
        probs = np.asarray(jax.nn.softmax(logits / 0.8, axis=-1))[0]
        order = np.argsort(-probs)
        cum = np.cumsum(probs[order])
        nucleus = set(order[:int(np.searchsorted(cum, 0.6)) + 1]
                      .tolist())
        seen0, seen1 = set(), set()
        for i in range(200):
            keys = np.stack([np.asarray([i, 1], np.uint32),
                             np.asarray([i, 2], np.uint32)])
            out = sample_dynamic(logits, jnp.asarray(keys), temp,
                                 top_k, top_p, V)
            seen0.add(int(out[0]))
            seen1.add(int(out[1]))
        assert seen0 <= nucleus, (seen0, nucleus)
        # the disabled row samples from the full distribution — it
        # must escape the nucleus at least once across 200 draws
        assert any(t not in nucleus for t in seen1)

    def test_top_p_validation_at_submit(self, gpt):
        model, params = gpt
        engine = Engine(model, params, max_slots=1,
                        prompt_buckets=(8,))
        sched = Scheduler(engine)
        with pytest.raises(ValueError, match="top_p"):
            sched.submit(Request(prompt=np.zeros(4, np.int32),
                                 max_new_tokens=2, temperature=1.0,
                                 top_p=1.5))


class TestSamplingDeterminism:
    def test_tokens_independent_of_cotenants(self, gpt):
        """A sampled request carries its own rng (seeded at admission):
        running alone or beside other traffic must not change its
        tokens."""
        model, params = gpt
        rng = np.random.default_rng(7)
        prompt = rng.integers(0, model.cfg.vocab_size,
                              size=(6,)).astype(np.int32)

        def run(extra_traffic):
            engine = Engine(model, params, max_slots=2,
                            prompt_buckets=(8,))
            sched = Scheduler(engine)
            req = sched.submit(Request(
                prompt=prompt, max_new_tokens=5, temperature=0.9,
                top_k=20, seed=123))
            if extra_traffic:
                for i in range(3):
                    sched.submit(Request(
                        prompt=rng.integers(
                            0, model.cfg.vocab_size,
                            size=(4 + i,)).astype(np.int32),
                        max_new_tokens=4, temperature=1.3, seed=i))
            sched.drain()
            return list(req.tokens)

        assert run(False) == run(True)


class TestSoakZeroRetraces:
    def test_steady_state_soak(self, gpt):
        """The acceptance soak: >= 3 prompt-length buckets, mixed
        temperatures / top_k / top_p / eos / budgets, admissions and
        evictions interleaving across 14 requests through 3 slots —
        zero jaxpr traces after warmup.  The engine's retrace_guards
        (budget: decode_step/admit/release = 1, prefill = #buckets)
        raise RetraceError on any excess trace, and the process-wide
        trace-event counter cross-checks the whole soak.  Nucleus
        (top_p) traffic rides the same executable as everything else
        (the ISSUE-3 plumbing contract: per-slot device-array
        params, budgets unchanged)."""
        model, params = gpt
        engine = Engine(model, params, max_slots=3,
                        prompt_buckets=(4, 8, 16))
        sched = Scheduler(engine)
        engine.warmup()
        assert engine.trace_counts == {
            "decode_step": 1, "prefill": 3, "admit": 1, "release": 1}

        rng = np.random.default_rng(11)
        before = tracecheck.trace_event_count()
        cases = [
            (3, 4, 0.0, None, None, None),
            (7, 3, 0.8, 20, None, None),
            (12, 5, 1.2, 5, None, 0.9), (2, 6, 0.0, None, 17, None),
            (8, 2, 0.5, None, None, 0.5),
            (16, 4, 0.0, None, None, None),
            (5, 3, 1.0, 50, 3, 0.95), (4, 5, 0.0, None, None, None),
            (9, 4, 0.7, 10, None, None), (1, 2, 0.0, None, None, None),
            (13, 3, 1.5, 2, None, 1.0), (6, 6, 0.0, None, 900, None),
            (11, 2, 0.9, None, None, 0.7),
            (8, 4, 0.0, None, None, None),
        ]
        reqs = []
        for i, (L, n, t, k, eos, p) in enumerate(cases):
            reqs.append(sched.submit(Request(
                prompt=rng.integers(0, model.cfg.vocab_size,
                                    size=(L,)).astype(np.int32),
                max_new_tokens=n, temperature=t, top_k=k, top_p=p,
                eos_id=eos, seed=i)))
        events = sched.drain()
        assert tracecheck.trace_event_count() == before, (
            "steady-state soak retraced after warmup")
        assert engine.trace_counts == {
            "decode_step": 1, "prefill": 3, "admit": 1, "release": 1}
        # every request produced tokens and respected its budget
        for (L, n, t, k, eos, p), r in zip(cases, reqs):
            assert 1 <= len(r.tokens) <= n
            if eos is None:
                assert len(r.tokens) == n
        assert len(events) == sum(len(r.tokens) for r in reqs)

    def test_unbucketable_prompt_raises_not_retraces(self, gpt):
        model, params = gpt
        engine = Engine(model, params, max_slots=1,
                        prompt_buckets=(4,))
        with pytest.raises(ValueError, match="bucket"):
            engine.admit(0, np.zeros(5, np.int32), max_new_tokens=1)

    def test_guard_raises_on_forced_retrace(self, gpt):
        """The guard is live, not decorative: bypassing the bucketer
        with a second prefill shape beyond the budget must raise
        RetraceError (this is what a shape leak in production would
        look like)."""
        model, params = gpt
        engine = Engine(model, params, max_slots=1,
                        prompt_buckets=(4,))
        engine.warmup()
        with pytest.raises(RetraceError):
            engine._prefill(engine._variables,
                            jnp.zeros((1, 6), jnp.int32), np.int32(6))


class TestInferenceServer:
    def test_streaming_and_metrics(self, gpt):
        model, params = gpt
        rows = []
        writer = MetricsWriter(sink=lambda s, m: rows.append((s, m)))
        server = InferenceServer(
            model, params, max_slots=2, prompt_buckets=(4, 8),
            metrics=writer, metrics_interval=2)
        rng = np.random.default_rng(13)
        with server:
            h1 = server.submit(
                rng.integers(0, model.cfg.vocab_size, size=(3,)),
                max_new_tokens=4)
            h2 = server.submit(
                rng.integers(0, model.cfg.vocab_size, size=(6,)),
                max_new_tokens=3, temperature=0.8, seed=4)
            streamed = list(h1.stream(timeout=300))
            assert streamed == h1.result(timeout=300)
            assert len(streamed) == 4
            assert len(h2.result(timeout=300)) == 3
        assert rows, "metrics never emitted"
        steps = [s for s, _ in rows]
        assert steps == sorted(steps)
        for _, m in rows:
            assert {"tokens_per_sec", "occupancy",
                    "queue_depth"} <= set(m)
            assert 0.0 <= m["occupancy"] <= 1.0

    def test_greedy_parity_through_server(self, gpt):
        model, params = gpt
        rng = np.random.default_rng(17)
        prompt = rng.integers(0, model.cfg.vocab_size,
                              size=(5,)).astype(np.int32)
        ref = np.asarray(generate(
            model, params, jnp.asarray(prompt[None]),
            max_new_tokens=5))[0, 5:]
        with InferenceServer(model, params, max_slots=2,
                             prompt_buckets=(8,)) as server:
            got = server.submit(
                prompt, max_new_tokens=5).result(timeout=300)
        np.testing.assert_array_equal(np.asarray(got), ref)

    def test_shutdown_without_drain_cancels(self, gpt):
        from apex_tpu.serving import ServerClosed

        model, params = gpt
        server = InferenceServer(model, params, max_slots=1,
                                 prompt_buckets=(4,))
        server.start(warmup=False)
        h = server.submit(np.zeros(3, np.int32), max_new_tokens=200)
        server.shutdown(wait=False, timeout=60)
        with pytest.raises((ServerClosed, TimeoutError)):
            h.result(timeout=60)

    def test_worker_crash_cancels_clients(self, gpt):
        """An engine failure inside the serving loop must not strand
        clients: handles raise ServerClosed, submit refuses, and the
        root cause is preserved on server.error."""
        from apex_tpu.serving import ServerClosed

        model, params = gpt
        server = InferenceServer(model, params, max_slots=1,
                                 prompt_buckets=(4,))
        boom = RuntimeError("engine exploded")

        def exploding_step():
            raise boom

        server.scheduler.run_step = exploding_step
        server.start(warmup=False)
        h = server.submit(np.zeros(3, np.int32), max_new_tokens=4)
        with pytest.raises(ServerClosed):
            h.result(timeout=60)
        with pytest.raises(ServerClosed):
            server.submit(np.zeros(2, np.int32), max_new_tokens=1)
        assert server.error is boom
        server.shutdown(timeout=60)

    def test_submit_after_shutdown_raises(self, gpt):
        from apex_tpu.serving import ServerClosed

        model, params = gpt
        server = InferenceServer(model, params, max_slots=1,
                                 prompt_buckets=(4,))
        server.start(warmup=False)
        server.shutdown()
        with pytest.raises(ServerClosed):
            server.submit(np.zeros(2, np.int32), max_new_tokens=1)


class TestHandleErrorContract:
    """RequestHandle.stream/result error taxonomy (docs/resilience.md):
    TimeoutError = retryable "no token yet"; ServerClosed /
    RequestFailed = terminal.  A shutdown race must never surface as a
    bare timeout."""

    def test_timeout_is_retryable_not_terminal(self, gpt):
        model, params = gpt
        server = InferenceServer(model, params, max_slots=1,
                                 prompt_buckets=(4,))
        server.start(warmup=False)      # first token needs a compile
        try:
            h = server.submit(np.zeros(3, np.int32), max_new_tokens=3)
            with pytest.raises(TimeoutError, match="retryable"):
                h.result(timeout=1e-4)
            # the request was NOT terminated by that timeout: the same
            # handle still completes
            assert len(h.result(timeout=300)) == 3
            assert h.error is None
        finally:
            server.shutdown(timeout=60)

    def test_shutdown_surfaces_terminal_not_timeout(self, gpt):
        from apex_tpu.serving import ServerClosed

        model, params = gpt
        server = InferenceServer(model, params, max_slots=1,
                                 prompt_buckets=(4,))
        server.start(warmup=False)
        h = server.submit(np.zeros(3, np.int32), max_new_tokens=200)
        # wait=False cancels in-flight requests; after the worker has
        # joined, the handle MUST report the terminal ServerClosed even
        # with a tiny timeout — the old shutdown race surfaced here as
        # a bare TimeoutError
        server.shutdown(wait=False, timeout=120)
        with pytest.raises(ServerClosed):
            h.result(timeout=0.001)
        with pytest.raises(ServerClosed):
            list(h.stream(timeout=0.001))
        assert isinstance(h.error, ServerClosed)

    def test_deadline_failure_is_request_failed(self, gpt):
        from apex_tpu.serving import RequestFailed

        model, params = gpt
        server = InferenceServer(model, params, max_slots=1,
                                 prompt_buckets=(4,))
        with server:
            h = server.submit(np.zeros(3, np.int32),
                              max_new_tokens=100, deadline=1e-4)
            with pytest.raises(RequestFailed, match="deadline"):
                h.result(timeout=300)
            # the failure is per-request: the server keeps serving
            h2 = server.submit(np.zeros(2, np.int32), max_new_tokens=2)
            assert len(h2.result(timeout=300)) == 2
            assert server.health()["ready"]


class TestDrainKillAndHealthFields:
    """Replica-lifecycle plumbing for the fleet router
    (docs/serving.md health table, docs/fleet.md): graceful drain
    evicts with ReplicaDraining and releases the engine; kill abandons
    the engine and cancels with ServerClosed; health() carries
    draining / uptime_s / queue_depth.  [one server per test — warmup
    dominates, so the assertions are batched along each lifecycle]"""

    def test_drain_lifecycle_health_fields_and_eviction(self, gpt):
        from apex_tpu.serving import ReplicaDraining, ServerClosed

        model, params = gpt
        server = InferenceServer(model, params, max_slots=1,
                                 prompt_buckets=(4,))
        server.start(warmup=False)      # executables compile on demand
        h = server.submit(np.zeros(3, np.int32), max_new_tokens=200)
        for _ in h.stream(timeout=300):
            break                       # mid-decode, prefix streamed
        health = server.health()
        assert health["draining"] is False
        assert health["uptime_s"] >= 0.0
        assert "queue_depth" in health and "drain_evicted" in health
        server.begin_drain()
        with pytest.raises(ReplicaDraining):
            h.result(timeout=300)
        # the migrate signal is a ServerClosed subclass: plain clients
        # need no special case — and the streamed prefix survives
        assert isinstance(h.error, ServerClosed)
        assert len(h.tokens_so_far) >= 1
        health = server.health()
        assert health["draining"] is True and server.draining
        # still alive, but a load balancer must stop routing here
        assert health["status"] == "serving"
        assert health["ready"] is False
        assert health["drain_evicted"] == 1
        with pytest.raises(ServerClosed, match="draining"):
            server.submit(np.zeros(3, np.int32), max_new_tokens=1)
        server.shutdown(timeout=60)

    def test_kill_cancels_clients_and_reports_failed(self, gpt):
        from apex_tpu.serving import ServerClosed

        model, params = gpt
        server = InferenceServer(model, params, max_slots=1,
                                 prompt_buckets=(4,))
        server.start(warmup=False)
        h = server.submit(np.zeros(3, np.int32), max_new_tokens=200)
        server.kill()
        with pytest.raises(ServerClosed):
            h.result(timeout=300)
        health = server.health()
        assert health["status"] == "failed" and not health["ready"]
        assert server.error is not None
        server.kill()                           # idempotent
        server.shutdown()                       # and shutdown-safe


class TestLatencySummarySnapshotRace:
    """Regression for a real pre-existing cross-thread race the
    graftlint concurrency pass flagged (ISSUE 9): the worker thread
    appends to the ``_ttft``/``_step_times`` reservoirs while any
    thread (fleet supervisor SLO probes, clients) snapshots them in
    ``latency_summary()`` — and iterating a deque during an append
    raises ``RuntimeError``.  Both sides now hold ``_lat_lock``; this
    hammer fails within milliseconds on the unlocked code."""

    def test_snapshot_survives_concurrent_appends(self):
        import threading
        import time as _time
        from collections import deque

        srv = InferenceServer.__new__(InferenceServer)
        srv._lat_lock = threading.Lock()
        srv._ttft = deque(maxlen=2048)
        srv._step_times = deque(maxlen=4096)
        for i in range(512):                    # pre-fill: long iteration
            with srv._lat_lock:
                srv._ttft.append(0.01 * i)
                srv._step_times.append(0.002)
        stop = threading.Event()
        errors = []

        def worker():
            i = 0
            try:
                while not stop.is_set():
                    with srv._lat_lock:         # the worker's append path
                        srv._ttft.append(0.01 * (i % 7))
                        srv._step_times.append(0.002 + 1e-5 * (i % 3))
                    i += 1
            except BaseException as exc:        # pragma: no cover
                errors.append(exc)

        t = threading.Thread(target=worker)
        t.start()
        try:
            deadline = _time.monotonic() + 0.8
            while _time.monotonic() < deadline:
                out = srv.latency_summary()
                assert set(out) == {"ttft_p50_s", "ttft_p99_s",
                                    "step_ms_p50", "step_ms_p99"}
        finally:
            stop.set()
            t.join()
        assert errors == []
