"""graftlint rule fixtures — one flagged and one clean source per rule
(trace-hygiene AND the whole-program concurrency rules), plus
suppression/trace-inference/CLI coverage, the deliberate
lock-inversion fixture pair (flagged, and silenced by its suppression
twin), the machine-readable ``--format=json`` record contract, the
per-file AST cache + timing budget, and the gate that the repo's own
tree stays clean (the CI job's in-process twin).

Pure AST work, no jax needed — but the shared conftest imports jax, so
these run inside the normal hermetic suite.  The *runtime* twin of the
concurrency rules is covered in ``tests/test_lockcheck.py``.
"""

import json
import os
import textwrap

import pytest

from tools.graftlint.core import (
    all_program_rules,
    all_rules,
    lint_path,
    lint_paths,
    lint_source,
    main,
    run_stats,
)


def lint(src, rule=None):
    """Findings for dedented ``src``, optionally one rule only."""
    return lint_source(textwrap.dedent(src), "<fixture>",
                       select=[rule] if rule else None)


def names(findings):
    return [f.rule for f in findings]


def test_registry_has_at_least_eight_rules():
    rules = all_rules()
    assert len(rules) >= 8
    for name, rule in rules.items():
        assert rule.name == name and rule.summary


def test_program_registry_has_the_concurrency_rules():
    rules = all_program_rules()
    assert {"unguarded-shared-field", "guarded-by-violation",
            "requires-lock-violation", "lock-order-cycle",
            "bf16-unsafe-reduction", "master-weight-violation",
            "unscaled-grad-use", "redundant-cast", "quant-code-arith",
            "unbound-axis-name", "spec-mesh-mismatch",
            "unreplicated-out-spec", "host-sync-in-step",
            "donation-after-use"} \
        <= set(rules)
    for name, rule in rules.items():
        assert rule.name == name and rule.summary
    # the two registries never collide on a name
    assert not set(rules) & set(all_rules())


# ----------------------------------------------------- rule fixtures

class TestEnvReadInTrace:
    RULE = "env-read-in-trace"

    def test_flagged_inside_jitted_function(self):
        found = lint("""
            import os, jax

            @jax.jit
            def step(x):
                mode = os.environ.get("APEX_TPU_DECODE_ATTN", "auto")
                return x if mode == "einsum" else -x
        """, self.RULE)
        assert names(found) == [self.RULE]

    def test_flagged_inside_module_call(self):
        found = lint("""
            import os
            import flax.linen as nn

            class Attn(nn.Module):
                def __call__(self, x):
                    if os.getenv("FLAG"):
                        return x
                    return -x
        """, self.RULE)
        assert names(found) == [self.RULE]

    def test_module_level_read_near_trace_paths_is_advisory(self):
        found = lint("""
            import os, jax

            DEBUG = os.environ.get("DEBUG", "0")

            @jax.jit
            def f(x):
                return x
        """, self.RULE)
        assert names(found) == [self.RULE]
        assert "captured at import time" in found[0].message

    def test_clean_untraced_helper(self):
        assert lint("""
            import os

            def configure():
                return os.environ.get("HOME", "/")
        """, self.RULE) == []


class TestTracedBranch:
    RULE = "traced-branch"

    def test_flagged_if_on_traced_value(self):
        found = lint("""
            import jax
            import jax.numpy as jnp

            @jax.jit
            def f(x):
                y = jnp.sum(x)
                if y > 0:
                    return y
                return -y
        """, self.RULE)
        assert names(found) == [self.RULE]

    def test_flagged_while_on_traced_value(self):
        found = lint("""
            import jax

            @jax.jit
            def f(x):
                while x.sum() > 1:
                    x = x / 2
                return x
        """, self.RULE)
        assert names(found) == [self.RULE]

    def test_flagged_branch_inside_nested_loss_fn_closure(self):
        # the canonical jit'd train_step with an inner loss_fn closing
        # over the batch — the nested def is part of the same trace
        found = lint("""
            import jax
            import jax.numpy as jnp

            @jax.jit
            def train_step(state, batch):
                def loss_fn(p):
                    if batch.sum() > 0:
                        return jnp.mean(p * batch)
                    return jnp.mean(p)
                return jax.grad(loss_fn)(state)
        """, self.RULE)
        assert names(found) == [self.RULE]

    def test_nested_def_params_are_tainted(self):
        found = lint("""
            import jax

            @jax.jit
            def f(x):
                def inner(y):
                    if y > 0:
                        return y
                    return -y
                return inner(x)
        """, self.RULE)
        assert names(found) == [self.RULE]

    def test_clean_config_typed_param_branch(self):
        # *Config-typed params are hashable static config: branching
        # on their fields specializes the trace on purpose
        assert lint("""
            import flax.linen as nn

            def norm(cfg: TransformerConfig, name: str):
                class Norm(nn.Module):
                    def __call__(self, x):
                        if cfg.norm == "rmsnorm":
                            return x * cfg.eps
                        return x
                return Norm(name=name)

            class Block(nn.Module):
                def __call__(self, x):
                    return norm(self.cfg, "pre")(x)
        """, self.RULE) == []

    def test_clean_annotated_static_flag_closure(self):
        # an unannotated closure flag would over-taint; `causal: bool`
        # marks it static for the whole nested trace
        assert lint("""
            import jax
            from jax import lax

            def accum(q, axis: str, causal: bool, scale: float):
                def tick(carry, t):
                    if causal:
                        carry = carry * scale
                    return carry, None
                return lax.scan(tick, q, None, length=4)
        """, self.RULE) == []

    def test_clean_shape_branch_and_none_check(self):
        assert lint("""
            import jax

            @jax.jit
            def f(x, mask=None):
                if x.shape[0] > 128:
                    x = x[:128]
                if mask is not None:
                    x = x * mask
                return x
        """, self.RULE) == []


class TestJitUnhashableDefault:
    RULE = "jit-unhashable-default"

    def test_flagged_dict_default(self):
        found = lint("""
            import jax

            @jax.jit
            def f(x, opts={}):
                return x
        """, self.RULE)
        assert names(found) == [self.RULE]

    def test_flagged_call_site_list_default(self):
        found = lint("""
            import jax

            def f(x, axes=[0, 1]):
                return x

            g = jax.jit(f)
        """, self.RULE)
        assert names(found) == [self.RULE]

    def test_clean_hashable_defaults(self):
        assert lint("""
            import jax

            @jax.jit
            def f(x, axes=(0, 1), scale=1.0, mask=None):
                return x
        """, self.RULE) == []


class TestJitMissingDonate:
    RULE = "jit-missing-donate"

    def test_flagged_train_step_without_donate(self):
        found = lint("""
            import jax

            @jax.jit
            def train_step(state, batch):
                return state
        """, self.RULE)
        assert names(found) == [self.RULE]

    def test_clean_with_donate_argnums(self):
        assert lint("""
            import functools
            import jax

            @functools.partial(jax.jit, donate_argnums=(0,))
            def train_step(state, batch):
                return state
        """, self.RULE) == []

    def test_clean_no_state_shaped_params(self):
        assert lint("""
            import jax

            @jax.jit
            def forward(params, x):
                return x
        """, self.RULE) == []


class TestLruCacheHazard:
    RULE = "lru-cache-hazard"

    def test_flagged_env_read_under_lru_cache(self):
        found = lint("""
            import functools, os

            @functools.lru_cache(maxsize=8)
            def compiled_run(n):
                return os.environ.get("APEX_TPU_DECODE_ATTN", "auto"), n
        """, self.RULE)
        assert names(found) == [self.RULE]

    def test_flagged_unhashable_default(self):
        found = lint("""
            import functools

            @functools.lru_cache
            def build(shape=[1, 2]):
                return tuple(shape)
        """, self.RULE)
        assert names(found) == [self.RULE]

    def test_clean_hashable_pure(self):
        assert lint("""
            import functools

            @functools.lru_cache(maxsize=None)
            def build(shape=(1, 2), dtype="f32"):
                return shape, dtype
        """, self.RULE) == []


class TestTimeInTrace:
    RULE = "time-in-trace"

    def test_flagged_wallclock_and_np_random(self):
        found = lint("""
            import time, jax
            import numpy as np

            @jax.jit
            def f(x):
                t0 = time.time()
                noise = np.random.randn(4)
                return x + noise, t0
        """, self.RULE)
        assert names(found) == [self.RULE, self.RULE]

    def test_clean_timing_outside_jit(self):
        assert lint("""
            import time, jax

            @jax.jit
            def f(x):
                return x * 2

            def bench(x):
                t0 = time.time()
                f(x)
                return time.time() - t0
        """, self.RULE) == []


class TestHostSyncInTrace:
    RULE = "host-sync-in-trace"

    def test_flagged_item_and_float(self):
        found = lint("""
            import jax

            @jax.jit
            def f(x):
                s = x.sum()
                return float(s), s.item()
        """, self.RULE)
        assert names(found) == [self.RULE, self.RULE]

    def test_flagged_float_inside_nested_loss_fn(self):
        found = lint("""
            import jax
            import jax.numpy as jnp

            @jax.jit
            def train_step(params, batch):
                def loss_fn(p):
                    return float(jnp.mean(p * batch))
                return jax.grad(loss_fn)(params)
        """, self.RULE)
        assert names(found) == [self.RULE]

    def test_clean_static_conversions(self):
        assert lint("""
            import jax

            @jax.jit
            def f(x):
                n = int(x.shape[0])
                return x[:n]
        """, self.RULE) == []


class TestPrintInTrace:
    RULE = "print-in-trace"

    def test_flagged_print_of_tracer(self):
        found = lint("""
            import jax

            @jax.jit
            def f(x):
                print(x)
                return x
        """, self.RULE)
        assert names(found) == [self.RULE]

    def test_flagged_fstring_of_tracer(self):
        found = lint("""
            import jax

            @jax.jit
            def f(x):
                msg = f"value = {x.sum()}"
                return x
        """, self.RULE)
        assert names(found) == [self.RULE]

    def test_flagged_fstring_in_nested_closure_and_no_duplicates(self):
        found = lint("""
            import jax

            @jax.jit
            def train_step(params, batch):
                def loss_fn(p):
                    msg = f"loss input {batch.sum()}"
                    return (p * batch).sum()
                return jax.grad(loss_fn)(params)
        """, self.RULE)
        assert names(found) == [self.RULE]   # exactly once

    def test_clean_fstring_in_raise_and_outside_print(self):
        assert lint("""
            import jax

            @jax.jit
            def f(x):
                if x.ndim != 2:
                    raise ValueError(f"need 2D, got {x.ndim}, {x}")
                return x

            def report(y):
                print(f"loss = {y}")
        """, self.RULE) == []


class TestMutableGlobalInTrace:
    RULE = "mutable-global-in-trace"

    def test_flagged_module_list_append(self):
        found = lint("""
            import jax

            HISTORY = []

            @jax.jit
            def f(x):
                HISTORY.append(1)
                return x
        """, self.RULE)
        assert names(found) == [self.RULE]

    def test_flagged_global_rebind(self):
        found = lint("""
            import jax

            STEPS = []

            @jax.jit
            def f(x):
                global STEPS
                STEPS = [x]
                return x
        """, self.RULE)
        assert names(found) == [self.RULE]

    def test_clean_local_container(self):
        assert lint("""
            import jax

            @jax.jit
            def f(x):
                parts = []
                parts.append(x)
                return parts[0]
        """, self.RULE) == []


# ----------------------------------------------------- suppressions

FLAGGED = """
    import os, jax

    @jax.jit
    def f(x):
        mode = os.getenv("MODE"){trailer}
        return x
"""


class TestSuppression:
    def test_trailing_disable(self):
        src = FLAGGED.format(
            trailer="  # graftlint: disable=env-read-in-trace")
        assert lint(src, "env-read-in-trace") == []

    def test_standalone_disable_covers_next_line(self):
        found = lint("""
            import os, jax

            @jax.jit
            def f(x):
                # graftlint: disable=env-read-in-trace
                mode = os.getenv("MODE")
                return x
        """, "env-read-in-trace")
        assert found == []

    def test_file_wide_disable(self):
        found = lint("""
            # graftlint: disable-file=env-read-in-trace
            import os, jax

            @jax.jit
            def f(x):
                mode = os.getenv("MODE")
                return x
        """, "env-read-in-trace")
        assert found == []

    def test_disable_all(self):
        src = FLAGGED.format(trailer="  # graftlint: disable=all")
        assert lint(src, "env-read-in-trace") == []

    def test_trailing_commentary_does_not_break_suppression(self):
        # the documented style: a suppression plus the why
        src = FLAGGED.format(
            trailer="  # graftlint: disable=env-read-in-trace — "
                    "host-only value, never traced")
        assert lint(src, "env-read-in-trace") == []

    def test_wrong_rule_does_not_suppress(self):
        src = FLAGGED.format(
            trailer="  # graftlint: disable=traced-branch")
        assert names(lint(src, "env-read-in-trace")) \
            == ["env-read-in-trace"]

    def test_not_traced_mark_opts_out(self):
        found = lint("""
            import os
            import flax.linen as nn

            class M(nn.Module):
                def __call__(self, x):  # graftlint: not-traced
                    return os.getenv("HOME"), x
        """, "env-read-in-trace")
        assert found == []

    def test_traced_mark_opts_in(self):
        found = lint("""
            import os

            def helper(x):  # graftlint: traced
                return os.getenv("HOME"), x
        """, "env-read-in-trace")
        assert names(found) == ["env-read-in-trace"]


# ------------------------------------------- trace-path inference

class TestTraceInference:
    def test_scan_callee_is_traced(self):
        found = lint("""
            import os
            from jax import lax

            def body(carry, x):
                flag = os.getenv("FLAG")
                return carry, x

            def run(xs):
                return lax.scan(body, 0, xs)
        """, "env-read-in-trace")
        assert names(found) == ["env-read-in-trace"]

    def test_transitive_same_file_helper(self):
        found = lint("""
            import os, jax

            def helper(x):
                return os.getenv("MODE"), x

            @jax.jit
            def f(x):
                return helper(x)
        """, "env-read-in-trace")
        assert names(found) == ["env-read-in-trace"]

    def test_fori_loop_body_is_traced(self):
        found = lint("""
            import os
            from jax import lax

            def body(i, x):
                return x * (2 if os.getenv("FLAG") else 3)

            def run(x):
                return lax.fori_loop(0, 10, body, x)
        """, "env-read-in-trace")
        assert names(found) == ["env-read-in-trace"]

    def test_cond_false_branch_is_traced(self):
        found = lint("""
            import os
            from jax import lax

            def on_false(x):
                return x * len(os.environ["SCALE"])

            def run(pred, x):
                return lax.cond(pred, lambda x: x, on_false, x)
        """, "env-read-in-trace")
        assert names(found) == ["env-read-in-trace"]

    def test_switch_branches_are_traced(self):
        found = lint("""
            import os
            from jax import lax

            def branch_b(x):
                return x + len(os.environ["B"])

            def run(i, x):
                return lax.switch(i, [lambda x: x, branch_b], x)
        """, "env-read-in-trace")
        # branch passed inside a list literal is not resolvable by
        # name-position — but passed positionally it must be
        found2 = lint("""
            import os
            from jax import lax

            def branch_b(x):
                return x + len(os.environ["B"])

            def run(i, x):
                return lax.switch(i, branch_b, x)
        """, "env-read-in-trace")
        assert names(found2) == ["env-read-in-trace"]

    def test_cond_predicate_name_is_not_marked_traced(self):
        # `flag` at cond's args[0] is the predicate, not a callable:
        # a same-named def must NOT become a trace path
        found = lint("""
            import os
            from jax import lax

            def flag():
                return os.getenv("FLAG") == "1"

            def run(flag, x):
                return lax.cond(flag, lambda x: x, lambda x: -x, x)
        """, "env-read-in-trace")
        assert found == []

    def test_kwargs_catchall_is_tainted(self):
        found = lint("""
            import jax

            @jax.jit
            def f(x, **kw):
                if kw["mask"].sum() > 0:
                    return x
                return -x
        """, "traced-branch")
        assert names(found) == ["traced-branch"]

    def test_parse_error_is_reported_not_raised(self):
        found = lint_source("def f(:\n", "<bad>")
        assert names(found) == ["parse-error"]

    def test_no_duplicate_findings_for_repeated_jit_sites(self):
        found = lint("""
            import jax

            def train_step(state, batch):
                return state

            a = jax.jit(train_step)
            b = jax.jit(train_step)
        """, "jit-missing-donate")
        assert names(found) == ["jit-missing-donate"]


# ---------------------------------------- concurrency (program) rules

class TestUnguardedSharedField:
    """C1: a field mutated from two thread groups — or mutated in one
    and iterated in another — needs a declared discipline."""

    RULE = "unguarded-shared-field"

    def test_flagged_client_write_worker_iteration(self):
        found = lint("""
            import threading

            class Server:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._handles = {}
                    self._thread = threading.Thread(target=self._serve)

                def submit(self, uid, h):
                    self._handles[uid] = h

                def _serve(self):
                    for uid in self._handles:
                        pass
        """, self.RULE)
        assert names(found) == [self.RULE]
        assert "Server._handles" in found[0].message
        assert "guarded-by" in found[0].message     # the fix is named

    def test_flagged_iteration_through_values_view(self):
        # regression: `for h in self._handles.values():` is the same
        # traversal hazard as iterating the dict directly (a live view
        # raises RuntimeError mid-mutation) — it was classified as a
        # plain read and the rule's flagship shape went unflagged
        found = lint("""
            import threading

            class Server:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._handles = {}
                    self._thread = threading.Thread(target=self._serve)

                def submit(self, uid, h):
                    self._handles[uid] = h

                def _serve(self):
                    for h in self._handles.values():
                        h.poke()
        """, self.RULE)
        assert names(found) == [self.RULE]
        assert "Server._handles" in found[0].message

    def test_flagged_writes_from_two_thread_roots(self):
        found = lint("""
            import threading

            class Pipeline:
                def __init__(self):
                    self._stop_evt = threading.Event()
                    self._buf = []
                    self._t1 = threading.Thread(target=self._produce)
                    self._t2 = threading.Thread(target=self._consume)

                def _produce(self):
                    self._buf.append(1)

                def _consume(self):
                    self._buf.pop()
        """, self.RULE)
        assert names(found) == [self.RULE]

    def test_clean_with_guarded_by_annotation(self):
        assert lint("""
            import threading

            class Server:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._handles = {}  # graftlint: guarded-by(_lock)
                    self._thread = threading.Thread(target=self._serve)

                def submit(self, uid, h):
                    with self._lock:
                        self._handles[uid] = h

                def _serve(self):
                    with self._lock:
                        for uid in self._handles:
                            pass
        """, self.RULE) == []

    def test_clean_with_justified_unguarded(self):
        assert lint("""
            import threading

            class Server:
                def __init__(self):
                    self._lock = threading.Lock()
                    # graftlint: unguarded(identity-keyed atomic dict ops, never iterated cross-thread)
                    self._handles = {}
                    self._thread = threading.Thread(target=self._serve)

                def submit(self, uid, h):
                    self._handles[uid] = h

                def _serve(self):
                    for uid in self._handles:
                        pass
        """, self.RULE) == []

    def test_flagged_unguarded_without_justification(self):
        found = lint("""
            import threading

            class Server:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._handles = {}  # graftlint: unguarded()
                    self._thread = threading.Thread(target=self._serve)

                def submit(self, uid, h):
                    self._handles[uid] = h

                def _serve(self):
                    for uid in self._handles:
                        pass
        """, self.RULE)
        assert names(found) == [self.RULE]
        assert "no justification" in found[0].message

    def test_clean_single_writer_scalar_publish(self):
        # the CPython-safe idiom: a scalar written from one group and
        # read elsewhere needs no annotation
        assert lint("""
            import threading

            class Server:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._stop = False
                    self._thread = threading.Thread(target=self._serve)

                def stop(self):
                    self._stop = True

                def _serve(self):
                    while not self._stop:
                        pass
        """, self.RULE) == []

    def test_clean_non_concurrent_class_is_out_of_scope(self):
        # no locks, no threads: plain single-threaded state machine
        assert lint("""
            class Plain:
                def __init__(self):
                    self._handles = {}

                def submit(self, uid, h):
                    self._handles[uid] = h

                def drain(self):
                    for uid in self._handles:
                        pass
        """, self.RULE) == []

    def test_thread_entry_mark_roots_a_group(self):
        # a private callback marked thread-entry runs on another
        # thread: its touches count as a separate group
        found = lint("""
            import threading

            class Router:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._tokens = []

                # graftlint: thread-entry(replica-worker)
                def _on_token(self, t):
                    self._tokens.append(t)

                def result(self):
                    return sorted(self._tokens)
        """, self.RULE)
        assert names(found) == [self.RULE]

    def test_single_threaded_mark_excludes_a_method(self):
        assert lint("""
            import threading

            class Router:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._tokens = []
                    self._thread = threading.Thread(target=self._run)

                def _run(self):
                    self._tokens.append(1)

                # graftlint: single-threaded(runs before start())
                def warmup(self):
                    for t in self._tokens:
                        pass
        """, self.RULE) == []


class TestGuardedByViolation:
    """C2: every access of a guarded-by field must hold the lock."""

    RULE = "guarded-by-violation"

    def test_flagged_unlocked_mutation(self):
        found = lint("""
            import threading

            class Worker:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._queue = []  # graftlint: guarded-by(_lock)
                    self._thread = threading.Thread(target=self._run)

                def push(self, x):
                    with self._lock:
                        self._queue.append(x)

                def _run(self):
                    self._queue.pop()
        """, self.RULE)
        assert names(found) == [self.RULE]
        assert "Worker._queue" in found[0].message

    def test_flagged_unlocked_atomic_access_of_declared_field(self):
        # regression: atomic ops (len, subscript load, membership)
        # never count toward the SHARING hazard, but a field DECLARED
        # guarded-by is checked at every access — the runtime
        # sanitizer enforces exactly that, so exempting them here let
        # a graftlint-clean accessor fail the strict chaos soaks
        found = lint("""
            import threading

            class Worker:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._queue = []  # graftlint: guarded-by(_lock)
                    self._thread = threading.Thread(target=self._run)

                def depth(self):
                    return len(self._queue)

                def _run(self):
                    with self._lock:
                        self._queue.pop()
        """, self.RULE)
        assert names(found) == [self.RULE]
        assert "Worker._queue" in found[0].message

    def test_clean_all_accesses_locked(self):
        assert lint("""
            import threading

            class Worker:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._queue = []  # graftlint: guarded-by(_lock)
                    self._thread = threading.Thread(target=self._run)

                def push(self, x):
                    with self._lock:
                        self._queue.append(x)

                def _run(self):
                    with self._lock:
                        self._queue.pop()
        """, self.RULE) == []

    def test_clean_condition_alias_satisfies_guard(self):
        # _cv = Condition(self._lock): holding the condition IS
        # holding the lock
        assert lint("""
            import threading

            class Worker:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._cv = threading.Condition(self._lock)
                    self._queue = []  # graftlint: guarded-by(_lock)

                def push(self, x):
                    with self._cv:
                        self._queue.append(x)
        """, self.RULE) == []

    def test_flagged_guard_that_is_not_a_lock(self):
        found = lint("""
            import threading

            class Worker:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._queue = []  # graftlint: guarded-by(_mutex)

                def push(self, x):
                    with self._lock:
                        self._queue.append(x)
        """, self.RULE)
        assert names(found) == [self.RULE]
        assert "not a lock attribute" in found[0].message

    def test_clean_lock_held_through_caller(self):
        # interprocedural: the lock is held at the call site, so the
        # callee's accesses are covered
        assert lint("""
            import threading

            class Worker:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._queue = []  # graftlint: guarded-by(_lock)

                def push(self, x):
                    with self._lock:
                        self._push_locked(x)

                def _push_locked(self, x):
                    self._queue.append(x)
        """, self.RULE) == []


class TestRequiresLockViolation:
    """C3: a requires-lock method must only be called holding it."""

    RULE = "requires-lock-violation"

    def test_flagged_unlocked_call(self):
        found = lint("""
            import threading

            class Breaker:
                def __init__(self):
                    self._mutex = threading.RLock()
                    self._fails = 0  # graftlint: guarded-by(_mutex)

                # graftlint: requires-lock(_mutex)
                def _eject(self):
                    self._fails = 0

                def trip(self):
                    self._eject()
        """, self.RULE)
        assert names(found) == [self.RULE]
        assert "Breaker._eject" in found[0].message

    def test_clean_locked_call_and_body_assumes_lock(self):
        # the marked body is analyzed as holding the lock, so its
        # guarded-field accesses need no nested with
        assert lint("""
            import threading

            class Breaker:
                def __init__(self):
                    self._mutex = threading.RLock()
                    self._fails = 0  # graftlint: guarded-by(_mutex)

                # graftlint: requires-lock(_mutex)
                def _eject(self):
                    self._fails = 0

                def trip(self):
                    with self._mutex:
                        self._eject()
        """, self.RULE) == []


class TestLockOrderCycle:
    """C4: cyclic with-lock nesting across the call graph — and the
    deliberate inversion fixture pair the CI gate is proven on."""

    RULE = "lock-order-cycle"

    INVERSION = """
        import threading

        class Pair:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:{trailer}
                        pass

            def two(self):
                with self._b:
                    with self._a:
                        pass
    """

    def test_flagged_two_lock_inversion_with_witnesses(self):
        found = lint(self.INVERSION.format(trailer=""), self.RULE)
        assert names(found) == [self.RULE]
        msg = found[0].message
        assert "Pair._a" in msg and "Pair._b" in msg
        assert "witnesses" in msg and "deadlock" in msg

    def test_suppression_comment_silences_the_inversion(self):
        # the fixture pair's twin: same inversion, suppressed at the
        # reported site with a justification
        src = self.INVERSION.format(
            trailer="  # graftlint: disable=lock-order-cycle — "
                    "fixture: intentional inversion, documented")
        assert lint(src, self.RULE) == []

    def test_flagged_interprocedural_self_edge_on_plain_lock(self):
        found = lint("""
            import threading

            class Re:
                def __init__(self):
                    self._lock = threading.Lock()

                def outer(self):
                    with self._lock:
                        self._inner()

                def _inner(self):
                    with self._lock:
                        pass
        """, self.RULE)
        assert names(found) == [self.RULE]
        assert "re-acquired while already held" in found[0].message

    def test_clean_reentrant_rlock_self_nesting(self):
        assert lint("""
            import threading

            class Re:
                def __init__(self):
                    self._mutex = threading.RLock()

                def outer(self):
                    with self._mutex:
                        self._inner()

                def _inner(self):
                    with self._mutex:
                        pass
        """, self.RULE) == []

    def test_clean_consistent_order(self):
        assert lint("""
            import threading

            class Pair:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def one(self):
                    with self._a:
                        with self._b:
                            pass

                def two(self):
                    with self._a:
                        with self._b:
                            pass
        """, self.RULE) == []

    def test_flagged_cross_class_cycle_through_typed_fields(self):
        # Gate holds its lock while calling into Owner (which takes
        # its own); Owner holds its lock while calling back into Gate
        # — a cycle spanning two classes, carried across ``self.f.m()``
        # typed-field call edges
        found = lint("""
            import threading

            class Gate:
                def __init__(self):
                    self._glock = threading.Lock()
                    self.owner = Owner()

                def check(self):
                    with self._glock:
                        self.owner.sync()

                def ping(self):
                    with self._glock:
                        pass

            class Owner:
                def __init__(self):
                    self._olock = threading.Lock()
                    self.gate = Gate()

                def sync(self):
                    with self._olock:
                        pass

                def run(self):
                    with self._olock:
                        self.gate.ping()
        """, self.RULE)
        assert len(found) >= 1
        assert any("Gate._glock" in f.message
                   and "Owner._olock" in f.message for f in found)

    def test_flagged_three_lock_cycle_oriented_against_the_sort(self):
        # regression: cycles are rebuilt from witnessed edges, not by
        # zipping the sorted SCC — this cycle's orientation (_a->_c,
        # _c->_b, _b->_a) shares no adjacent pair with the sorted node
        # order (a,b,c) and was silently dropped
        found = lint("""
            import threading

            class Tri:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()
                    self._c = threading.Lock()

                def one(self):
                    with self._a:
                        with self._c:
                            pass

                def two(self):
                    with self._c:
                        with self._b:
                            pass

                def three(self):
                    with self._b:
                        with self._a:
                            pass
        """, self.RULE)
        assert names(found) == [self.RULE]
        msg = found[0].message
        # the reported chain follows actual edges, all three witnessed
        assert "Tri._a -> Tri._c -> Tri._b" in msg
        assert msg.count("at ") == 3

    def test_flagged_multi_item_with_against_nested_reverse(self):
        # regression: `with self._a, self._b:` acquires left-to-right,
        # so it must record the a->b edge the nested form would — the
        # items of one With previously saw only the incoming held set
        found = lint("""
            import threading

            class Pair:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def one(self):
                    with self._a, self._b:
                        pass

                def two(self):
                    with self._b:
                        with self._a:
                            pass
        """, self.RULE)
        assert names(found) == [self.RULE]
        assert "Pair._a" in found[0].message
        assert "Pair._b" in found[0].message


# ----------------------------------------- precision (program) rules


class TestBf16UnsafeReduction:
    """P1: reductions must not accumulate in a low-precision dtype —
    inferred-bf16 operands, Pallas-kernel accumulators that follow a
    raw ``*_ref`` load, and traced mean-family reductions with no fp32
    anchor anywhere on the operand's flow."""

    RULE = "bf16-unsafe-reduction"

    def test_flagged_mean_on_inferred_bf16(self):
        found = lint("""
            import jax.numpy as jnp

            def attn_probs(scores):
                s16 = scores.astype(jnp.bfloat16)
                return jnp.mean(s16, axis=-1)
        """, self.RULE)
        assert names(found) == [self.RULE]
        assert "low-precision" in found[0].message

    def test_clean_fp32_anchor(self):
        assert lint("""
            import jax.numpy as jnp

            def attn_probs(scores):
                return jnp.mean(scores.astype(jnp.float32), axis=-1)
        """, self.RULE) == []

    def test_clean_fp32_dtype_kwarg(self):
        # dtype=jnp.float32 IS the fp32 accumulator, whatever the
        # operand's storage dtype
        assert lint("""
            import jax.numpy as jnp

            def attn_probs(scores):
                s16 = scores.astype(jnp.bfloat16)
                return jnp.mean(s16, axis=-1, dtype=jnp.float32)
        """, self.RULE) == []

    def test_flagged_pallas_kernel_raw_ref_reduction(self):
        # the kernel-accumulator fixture: a reduction on a raw ref
        # load follows the input dtype — a bf16 pool accumulates bf16
        found = lint("""
            import jax.numpy as jnp

            def _lse_kernel(x_ref, o_ref):
                x = x_ref[:]
                o_ref[:] = jnp.sum(x, axis=1)
        """, self.RULE)
        assert names(found) == [self.RULE]
        assert "Pallas" in found[0].message

    def test_clean_pallas_kernel_upcast_load(self):
        assert lint("""
            import jax.numpy as jnp

            def _lse_kernel(x_ref, o_ref):
                x = x_ref[:].astype(jnp.float32)
                o_ref[:] = jnp.sum(x, axis=1)
        """, self.RULE) == []

    def test_flagged_pallas_dot_without_preferred_element_type(self):
        # the MXU shape: without preferred_element_type=f32 the
        # contraction accumulates in the input dtype
        found = lint("""
            import jax
            import jax.numpy as jnp

            def _qk_kernel(q_ref, k_ref, s_ref):
                s_ref[:] = jax.lax.dot_general(
                    k_ref[:], q_ref[:], (((1,), (1,)), ((), ())))
        """, self.RULE)
        assert names(found) == [self.RULE]
        assert "preferred_element_type" in found[0].message

    def test_clean_pallas_dot_with_preferred_element_type(self):
        assert lint("""
            import jax
            import jax.numpy as jnp

            def _qk_kernel(q_ref, k_ref, s_ref):
                s_ref[:] = jax.lax.dot_general(
                    k_ref[:], q_ref[:], (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)
        """, self.RULE) == []

    def test_flagged_unanchored_traced_mean(self):
        # the resnet-head shape this rule caught for real: a traced
        # mean on a value that follows the compute dtype
        found = lint("""
            import jax
            import jax.numpy as jnp

            @jax.jit
            def head(x):
                return jnp.mean(x, axis=(1, 2))
        """, self.RULE)
        assert names(found) == [self.RULE]
        assert "no fp32 anchor" in found[0].message

    def test_reduce_fp32_mark_excuses_the_site(self):
        assert lint("""
            import jax
            import jax.numpy as jnp

            @jax.jit
            def head(x):
                return jnp.mean(x, axis=(1, 2))  # graftlint: reduce-fp32
        """, self.RULE) == []

    def test_clean_interprocedural_fp32_summary(self):
        # the helper's return dtype is known program-wide, so the
        # caller's reduction is anchored through the summary
        assert lint("""
            import jax
            import jax.numpy as jnp

            def to_probs(logits):
                return jax.nn.softmax(logits.astype(jnp.float32))

            @jax.jit
            def entropy(logits):
                p = to_probs(logits)
                return jnp.mean(p * jnp.log(p))
        """, self.RULE) == []


class TestMasterWeightViolation:
    """P2: the O2 contract — optimizer updates land on fp32 masters."""

    RULE = "master-weight-violation"

    MARKED = """
        import jax.numpy as jnp

        # graftlint: precision(master-fp32)
        def adam_update(grads, params):
            return params

        def step(state, grads):
            {prep}
            return adam_update(grads, {arg})
    """

    def test_flagged_marked_fn_called_with_bf16(self):
        found = lint(self.MARKED.format(
            prep="half = state.params.astype(jnp.bfloat16)",
            arg="half"), self.RULE)
        assert names(found) == [self.RULE]
        assert "master-fp32" in found[0].message

    def test_clean_marked_fn_called_with_fp32(self):
        assert lint(self.MARKED.format(
            prep="masters = state.params.astype(jnp.float32)",
            arg="masters"), self.RULE) == []

    def test_flagged_apply_updates_on_half_params(self):
        found = lint("""
            import jax.numpy as jnp
            import optax

            def step(params, updates):
                half = params.astype(jnp.float16)
                return optax.apply_updates(half, updates)
        """, self.RULE)
        assert names(found) == [self.RULE]
        assert "fp32 master" in found[0].message

    def test_clean_apply_updates_on_unknown_params(self):
        # params of unknown dtype are not flagged — the rule fires on
        # *proven* low precision, suppressions stay rare
        assert lint("""
            import optax

            def step(params, updates):
                return optax.apply_updates(params, updates)
        """, self.RULE) == []

    def test_flagged_param_downcast_inside_marked_body(self):
        found = lint("""
            import jax.numpy as jnp

            # graftlint: precision(master-fp32)
            def adam_update(grads, params):
                p = params.astype(jnp.bfloat16)
                return p + grads
        """, self.RULE)
        assert names(found) == [self.RULE]
        assert "masters stay fp32" in found[0].message

    # -- ISSUE-11 fixtures: the rule sees ZeRO-SHARDED master shards —
    # the shard-local update is the same marked call shape, so a half
    # shard tree is flagged and the fp32 shard tree is clean; ZeRO
    # cannot silently drop the fp32-master discipline.

    ZERO_SHARDED = """
        import jax
        import jax.numpy as jnp

        # graftlint: precision(master-fp32)
        def shard_update(grad_shards, master_shards):
            return master_shards

        def zero_step(state, grad_shards):
            {prep}
            return shard_update(grad_shards, shards)
    """

    def test_flagged_zero_update_on_half_master_shards(self):
        found = lint(self.ZERO_SHARDED.format(
            prep="shards = state.opt_state.master"
                 ".astype(jnp.bfloat16)"), self.RULE)
        assert names(found) == [self.RULE]
        assert "master-fp32" in found[0].message

    def test_clean_zero_update_on_fp32_master_shards(self):
        assert lint(self.ZERO_SHARDED.format(
            prep="shards = state.opt_state.master"
                 ".astype(jnp.float32)"), self.RULE) == []

    def test_flagged_zero_shard_downcast_inside_marked_body(self):
        # the shard-shaped twin of the body contract: a marked
        # shard-local update must not downcast its own master shards
        found = lint("""
            import jax.numpy as jnp

            # graftlint: precision(master-fp32)
            def shard_update(grad_shards, master_shards):
                m16 = master_shards.astype(jnp.float16)
                return m16 + grad_shards
        """, self.RULE)
        assert names(found) == [self.RULE]
        assert "masters stay fp32" in found[0].message


class TestUnscaledGradUse:
    """P3: grads carry the loss scale until unscale/apply_gradients —
    norms and clips computed before that silently track the scale."""

    RULE = "unscaled-grad-use"

    def test_flagged_clip_on_scaled_grads(self):
        found = lint("""
            import jax
            from apex_tpu.optim import clip_grad_norm

            def train_step(state, batch):
                def loss_fn(p):
                    return state.scale_loss((p * batch).sum())
                grads = jax.grad(loss_fn)(state.params)
                grads, norm = clip_grad_norm(grads, 1.0)
                new_state, ok = state.apply_gradients(grads=grads)
                return new_state
        """, self.RULE)
        assert names(found) == [self.RULE]
        assert "still carry the loss scale" in found[0].message

    def test_flagged_norm_of_value_and_grad_result(self):
        found = lint("""
            import jax
            from apex_tpu.utils.tree import tree_l2_norm

            def train_step(state, batch):
                def loss_fn(p):
                    return state.scale_loss((p * batch).sum())
                loss, grads = jax.value_and_grad(loss_fn)(state.params)
                gnorm = tree_l2_norm(grads)
                new_state, ok = state.apply_gradients(grads=grads)
                return new_state, gnorm
        """, self.RULE)
        assert names(found) == [self.RULE]

    def test_clean_unscale_before_clip(self):
        assert lint("""
            import jax
            from apex_tpu.optim import clip_grad_norm

            def train_step(state, batch):
                def loss_fn(p):
                    return state.scale_loss((p * batch).sum())
                grads = jax.grad(loss_fn)(state.params)
                grads = state.loss_scaler.unscale(
                    state.loss_scale_state, grads)
                grads, norm = clip_grad_norm(grads, 1.0)
                new_state, ok = state.apply_gradients(grads=grads)
                return new_state
        """, self.RULE) == []

    def test_clean_apply_gradients_unscales_internally(self):
        assert lint("""
            import jax

            def train_step(state, batch):
                def loss_fn(p):
                    return state.scale_loss((p * batch).sum())
                grads = jax.grad(loss_fn)(state.params)
                new_state, ok = state.apply_gradients(grads=grads)
                return new_state
        """, self.RULE) == []

    def test_clean_without_loss_scaling_in_scope(self):
        # no scale multiply anywhere: grads are unscaled, clip freely
        assert lint("""
            import jax
            from apex_tpu.optim import clip_grad_norm

            def train_step(params, batch):
                def loss_fn(p):
                    return (p * batch).sum()
                grads = jax.grad(loss_fn)(params)
                grads, norm = clip_grad_norm(grads, 1.0)
                return grads
        """, self.RULE) == []


class TestRedundantCast:
    """P4: chained astype — dead intermediate, precision round-trip."""

    RULE = "redundant-cast"

    def test_flagged_round_trip_chain(self):
        found = lint("""
            import jax.numpy as jnp

            def f(x):
                return x.astype(jnp.float32).astype(jnp.bfloat16)
        """, self.RULE)
        assert names(found) == [self.RULE]
        assert "dead" in found[0].message

    def test_flagged_same_dtype_twice(self):
        found = lint("""
            import jax.numpy as jnp

            def f(x):
                return x.astype(jnp.float32).astype(jnp.float32)
        """, self.RULE)
        assert names(found) == [self.RULE]
        assert "already produced" in found[0].message

    def test_clean_single_casts(self):
        assert lint("""
            import jax.numpy as jnp

            def f(x, out_dtype):
                y = x.astype(jnp.float32)
                return (y * 2).astype(out_dtype)
        """, self.RULE) == []

    def test_lowprec_mark_excuses_deliberate_round_trip(self):
        # quantize-dequantize simulation is a legitimate chain when
        # the justification is recorded
        assert lint("""
            import jax.numpy as jnp

            def quantize_sim(x):
                # graftlint: lowprec(round-trip simulates the bf16 storage path on purpose)
                return x.astype(jnp.bfloat16).astype(jnp.float32)
        """, self.RULE) == []


class TestQuantCodeArith:
    """P5: int8/fp8 values are *codes*; arithmetic outside a blessed,
    justified dequant site is flagged."""

    RULE = "quant-code-arith"

    def test_flagged_sum_over_codes(self):
        found = lint("""
            import jax.numpy as jnp

            def accumulate(codes):
                q = codes.astype(jnp.int8)
                return jnp.sum(q, axis=0)
        """, self.RULE)
        assert names(found) == [self.RULE]
        assert "quantization codes" in found[0].message

    def test_flagged_binop_on_codes(self):
        # the classic mistake: scaling the codes without widening
        # first — int8 * float silently promotes element-wise but the
        # intent was a dequant
        found = lint("""
            import jax.numpy as jnp

            def dequant_wrong(codes, scale):
                q = codes.astype(jnp.int8)
                return q * scale
        """, self.RULE)
        assert names(found) == [self.RULE]

    def test_clean_widening_accumulate(self):
        # the ddp.py int8-allreduce shape: widen to int32, then sum
        assert lint("""
            import jax.numpy as jnp

            def accumulate(codes):
                q = codes.astype(jnp.int8)
                return jnp.sum(q.astype(jnp.int32), axis=0)
        """, self.RULE) == []

    def test_clean_structural_ops_on_codes(self):
        assert lint("""
            import jax.numpy as jnp

            def pack(codes, n, m):
                q = codes.astype(jnp.int8)
                flat = jnp.pad(q.ravel(), (0, 3))
                return flat.reshape(n, m)
        """, self.RULE) == []

    def test_lowprec_mark_excuses_with_justification(self):
        # the suppressed twin of the flagged fixture
        assert lint("""
            import jax.numpy as jnp

            def saturating_sum(codes):
                q = codes.astype(jnp.int8)
                return jnp.sum(q, axis=0)  # graftlint: lowprec(int8 saturation is the desired clamp here)
        """, self.RULE) == []

    def test_nested_scope_walrus_does_not_pollute_outer_env(self):
        # regression: the NamedExpr harvest walked into nested defs,
        # so an inner `q := ...astype(int8)` marked the OUTER `q` as
        # quant and a clean fp32 sum was falsely flagged
        assert lint("""
            import jax.numpy as jnp

            def outer(codes, xs):
                def inner():
                    return (q := codes.astype(jnp.int8))
                q = xs.astype(jnp.float32)
                return jnp.sum(q, axis=0), inner
        """, self.RULE) == []

    def test_empty_lowprec_justification_is_itself_flagged(self):
        found = lint("""
            import jax.numpy as jnp

            def accumulate(codes):
                q = codes.astype(jnp.int8)
                return jnp.sum(q, axis=0)  # graftlint: lowprec()
        """, self.RULE)
        assert names(found) == [self.RULE]
        assert "no justification" in found[0].message


# ------------------------------------------- sharding pass (ISSUE-16)

class TestUnboundAxisName:
    """Rule S1: a collective naming an axis nothing binds."""

    RULE = "unbound-axis-name"

    def test_flagged_axis_outside_the_enclosing_binding(self):
        found = lint("""
            import jax
            import numpy as np
            from jax.sharding import Mesh, PartitionSpec as P

            mesh = Mesh(np.array(jax.devices()), ("data",))

            def step(state, batch):
                loss = (state - batch).mean()
                return state, jax.lax.pmean(loss, "model")

            sharded = jax.shard_map(
                step, mesh=mesh, in_specs=(P("data"), P("data")),
                out_specs=(P("data"), P()))
        """, self.RULE)
        assert names(found) == [self.RULE]
        assert "'model'" in found[0].message
        assert "binds only" in found[0].message

    def test_flagged_no_mesh_in_the_program_declares_the_axis(self):
        found = lint("""
            import jax

            def allreduce(x):
                return jax.lax.psum(x, "data")
        """, self.RULE)
        assert names(found) == [self.RULE]
        assert "no mesh" in found[0].message

    def test_clean_bound_axis_and_program_declared_axis(self):
        assert lint("""
            import jax
            import numpy as np
            from jax.sharding import Mesh, PartitionSpec as P

            mesh = Mesh(np.array(jax.devices()), ("data",))

            def step(state, batch):
                loss = (state - batch).mean()
                return state, jax.lax.pmean(loss, "data")

            sharded = jax.shard_map(
                step, mesh=mesh, in_specs=(P("data"), P("data")),
                out_specs=(P("data"), P()))

            def library_helper(x):
                # unwrapped, but SOME mesh declares "data": advisory
                # silence — the binding is a call-site property
                return jax.lax.psum(x, "data")
        """, self.RULE) == []

    def test_axis_constants_resolve_program_wide(self):
        # TENSOR_AXIS = "tensor" in core/mesh.py resolves at use sites
        found = lint("""
            import jax

            TENSOR_AXIS = "tensor"

            def f(x):
                return jax.lax.psum(x, TENSOR_AXIS)
        """, self.RULE)
        assert names(found) == [self.RULE]
        assert "'tensor'" in found[0].message

    def test_flagged_collective_permute_binds_its_axis(self):
        # a permute is not a reduction, but it DOES name an axis —
        # the 1F1B boundary hop must still point at a bound 'pipe'
        found = lint("""
            import jax
            import numpy as np
            from jax.sharding import Mesh, PartitionSpec as P

            mesh = Mesh(np.array(jax.devices()), ("data",))

            def hop(x):
                return jax.lax.ppermute(
                    x, "pipe", perm=[(0, 1), (1, 0)])

            g = jax.shard_map(hop, mesh=mesh, in_specs=(P("data"),),
                              out_specs=P("data"))
        """, self.RULE)
        assert names(found) == [self.RULE]
        assert "'pipe'" in found[0].message
        assert "binds only" in found[0].message

    def test_clean_collective_permute_on_a_pipe_mesh(self):
        assert lint("""
            import jax
            import numpy as np
            from jax.sharding import Mesh, PartitionSpec as P

            mesh = Mesh(np.array(jax.devices()).reshape(2, 4),
                        ("data", "pipe"))

            def hop(x):
                return jax.lax.ppermute(
                    x, "pipe", perm=[(0, 1), (1, 0)])

            g = jax.shard_map(hop, mesh=mesh, in_specs=(P("data"),),
                              out_specs=P("data"))
        """, self.RULE) == []


class TestSpecMeshMismatch:
    """Rule S2: P(...) axes the mesh lacks, or in_specs arity off."""

    RULE = "spec-mesh-mismatch"

    def test_flagged_spec_axis_not_on_the_mesh(self):
        found = lint("""
            import jax
            import numpy as np
            from jax.sharding import Mesh, PartitionSpec as P

            mesh = Mesh(np.array(jax.devices()), ("data",))

            def f(x):
                return x * 2

            g = jax.shard_map(f, mesh=mesh, in_specs=(P("tensor"),),
                              out_specs=P("data"))
        """, self.RULE)
        assert names(found) == [self.RULE]
        assert "'tensor'" in found[0].message
        assert "replication" in found[0].message

    def test_flagged_in_specs_arity_misaligned(self):
        found = lint("""
            import jax
            import numpy as np
            from jax.sharding import Mesh, PartitionSpec as P

            mesh = Mesh(np.array(jax.devices()), ("data",))

            def f(x, y):
                return x + y

            g = jax.shard_map(f, mesh=mesh, in_specs=(P("data"),),
                              out_specs=P("data"))
        """, self.RULE)
        assert names(found) == [self.RULE]
        assert "in_specs has 1 entry" in found[0].message

    def test_clean_matching_axes_and_arity(self):
        assert lint("""
            import jax
            import numpy as np
            from jax.sharding import Mesh, PartitionSpec as P

            mesh = Mesh(np.array(jax.devices()), ("data", "tensor"))

            def f(x, y):
                return x + y

            g = jax.shard_map(
                f, mesh=mesh, in_specs=(P("data"), P("tensor")),
                out_specs=P("data", "tensor"))
        """, self.RULE) == []

    def test_unresolvable_mesh_skips_not_guesses(self):
        # the mesh comes in as a parameter: nothing to check against
        assert lint("""
            import jax
            from jax.sharding import PartitionSpec as P

            def wrap(mesh, f):
                return jax.shard_map(f, mesh=mesh,
                                     in_specs=(P("anything"),),
                                     out_specs=P("anything"))
        """, self.RULE) == []


class TestUnreplicatedOutSpec:
    """Rule S3: out_specs=P() on a shard-divergent return."""

    RULE = "unreplicated-out-spec"

    def test_flagged_divergent_return_claims_replication(self):
        found = lint("""
            import jax
            import numpy as np
            from jax.sharding import Mesh, PartitionSpec as P

            mesh = Mesh(np.array(jax.devices()), ("data",))

            def shard_loss(state, batch):
                loss = (state - batch).mean()
                return loss

            g = jax.shard_map(
                shard_loss, mesh=mesh,
                in_specs=(P("data"), P("data")), out_specs=P())
        """, self.RULE)
        assert names(found) == [self.RULE]
        assert "DIFFERENT value" in found[0].message
        assert "check_vma" in found[0].message

    def test_clean_reduction_on_the_return_path(self):
        assert lint("""
            import jax
            import numpy as np
            from jax.sharding import Mesh, PartitionSpec as P

            mesh = Mesh(np.array(jax.devices()), ("data",))

            def shard_loss(state, batch):
                loss = (state - batch).mean()
                return jax.lax.pmean(loss, "data")

            g = jax.shard_map(
                shard_loss, mesh=mesh,
                in_specs=(P("data"), P("data")), out_specs=P())
        """, self.RULE) == []

    def test_clean_unknown_callee_may_reduce_internally(self):
        # flagging through an opaque helper would make every composed
        # pipeline a false positive — unknown calls sanitize
        assert lint("""
            import jax
            import numpy as np
            from jax.sharding import Mesh, PartitionSpec as P

            from somewhere import pipeline_fn

            mesh = Mesh(np.array(jax.devices()), ("data",))

            def shard_loss(state, batch):
                return pipeline_fn(state, batch)

            g = jax.shard_map(
                shard_loss, mesh=mesh,
                in_specs=(P("data"), P("data")), out_specs=P())
        """, self.RULE) == []

    def test_clean_replicated_inputs_cannot_diverge(self):
        assert lint("""
            import jax
            import numpy as np
            from jax.sharding import Mesh, PartitionSpec as P

            mesh = Mesh(np.array(jax.devices()), ("data",))

            def broadcast(state, batch):
                return (state - batch).mean()

            g = jax.shard_map(
                broadcast, mesh=mesh, in_specs=(P(), P()),
                out_specs=P())
        """, self.RULE) == []

    def test_flagged_permute_does_not_sanitize_divergence(self):
        # a ppermute MOVES shard-divergent data between shards — the
        # output is exactly as divergent as the input, so it must not
        # launder a P() out_spec the way a psum/pmean does
        found = lint("""
            import jax
            import numpy as np
            from jax.sharding import Mesh, PartitionSpec as P

            mesh = Mesh(np.array(jax.devices()), ("pipe",))

            def hop(acts):
                moved = jax.lax.ppermute(
                    acts, "pipe", perm=[(0, 1), (1, 0)])
                return moved

            g = jax.shard_map(hop, mesh=mesh,
                              in_specs=(P("pipe"),), out_specs=P())
        """, self.RULE)
        assert names(found) == [self.RULE]
        assert "DIFFERENT value" in found[0].message

    def test_clean_permute_then_reduction_on_the_return_path(self):
        assert lint("""
            import jax
            import numpy as np
            from jax.sharding import Mesh, PartitionSpec as P

            mesh = Mesh(np.array(jax.devices()), ("pipe",))

            def hop(acts):
                moved = jax.lax.ppermute(
                    acts, "pipe", perm=[(0, 1), (1, 0)])
                return jax.lax.psum(moved.mean(), "pipe")

            g = jax.shard_map(hop, mesh=mesh,
                              in_specs=(P("pipe"),), out_specs=P())
        """, self.RULE) == []


class TestHostSyncInStep:
    """Rule S4: device->host sync inside a ``# graftlint: hot-step``
    function — the static twin of shardcheck's transfer windows."""

    RULE = "host-sync-in-step"

    def test_flagged_float_of_jitted_step_output(self):
        found = lint("""
            import jax

            @jax.jit
            def train_step(state, batch):
                return state, batch.sum()

            def run(state, batches):  # graftlint: hot-step
                for b in batches:
                    state, loss = train_step(state, b)
                    loss = float(loss)
                return state
        """, self.RULE)
        assert names(found) == [self.RULE]
        assert "float()" in found[0].message
        assert "hot-step" in found[0].message

    def test_flagged_asarray_and_item_on_device_values(self):
        found = lint("""
            import jax
            import numpy as np

            step = jax.jit(lambda s, b: (s, b))

            def decode(state, batch):  # graftlint: hot-step
                state, toks = step(state, batch)
                out = np.asarray(toks)
                n = toks.item()
                return out, n
        """, self.RULE)
        assert sorted(names(found)) == [self.RULE, self.RULE]

    def test_clean_sync_on_host_values(self):
        assert lint("""
            def run(cfg, batches):  # graftlint: hot-step
                total = 0.0
                for b in batches:
                    total += float(b)
                return total
        """, self.RULE) == []

    def test_unmarked_function_is_out_of_scope(self):
        # the blast radius is exactly the annotated step set
        assert lint("""
            import jax

            @jax.jit
            def train_step(state, batch):
                return state, batch.sum()

            def run(state, batches):
                for b in batches:
                    state, loss = train_step(state, b)
                    loss = float(loss)
                return state
        """, self.RULE) == []

    def test_taint_clears_through_a_justified_device_get(self):
        # the repo's fixed loop shape: ONE justified end-of-step fetch,
        # after which the fetched names are host values — the float()
        # on the next line is clean, not a second finding
        assert lint("""
            import jax

            step = jax.jit(lambda s, b: (s, b))

            def run(state, b):  # graftlint: hot-step
                state, loss = step(state, b)
                # graftlint: unsharded(end-of-step logging read)
                loss = jax.device_get(loss)
                return state, float(loss)
        """, self.RULE) == []


class TestDonationAfterUse:
    """Rule S5: a donated buffer read after the donating call."""

    RULE = "donation-after-use"

    def test_flagged_read_after_donating_call(self):
        found = lint("""
            import jax

            def do_step(s, b):
                return s + b

            step = jax.jit(do_step, donate_argnums=(0,))

            def train(state, batch):
                new_state = step(state, batch)
                print(state.shape)
                return new_state
        """, self.RULE)
        assert names(found) == [self.RULE]
        assert "`state` was donated" in found[0].message
        assert "garbage" in found[0].message

    def test_flagged_through_partial_jit_decorator(self):
        found = lint("""
            import functools
            import jax

            @functools.partial(jax.jit, donate_argnums=(0,))
            def train_step(state, batch):
                return state + batch

            def run(state, batch):
                out = train_step(state, batch)
                return state, out
        """, self.RULE)
        assert names(found) == [self.RULE]

    def test_clean_rebind_idiom(self):
        # `state = step(state, ...)` — the donated name is fresh again
        assert lint("""
            import jax

            def do_step(s, b):
                return s + b

            step = jax.jit(do_step, donate_argnums=(0,))

            def train(state, batches):
                for b in batches:
                    state = step(state, b)
                return state
        """, self.RULE) == []

    def test_clean_without_donation(self):
        assert lint("""
            import jax

            def do_step(s, b):
                return s + b

            step = jax.jit(do_step)

            def train(state, batch):
                new_state = step(state, batch)
                return state, new_state
        """, self.RULE) == []


class TestShardingSuppression:
    """The ``unsharded(<why>)`` escape hatch, and its empty-why twin
    being itself flagged (the guarded-by/lowprec convention)."""

    HOT = """
        import jax

        @jax.jit
        def train_step(state, batch):
            return state, batch.sum()

        def run(state, b):  # graftlint: hot-step
            state, loss = train_step(state, b)
            loss = float(loss){mark}
            return state
    """

    def test_justified_unsharded_silences(self):
        src = self.HOT.format(
            mark="  # graftlint: unsharded(demo logging)")
        assert lint(src, "host-sync-in-step") == []

    def test_standalone_unsharded_covers_the_next_line(self):
        src = self.HOT.format(mark="").replace(
            "            loss = float(loss)",
            "            # graftlint: unsharded(demo logging)\n"
            "            loss = float(loss)")
        assert lint(src, "host-sync-in-step") == []

    def test_empty_unsharded_justification_is_itself_flagged(self):
        src = self.HOT.format(mark="  # graftlint: unsharded()")
        found = lint(src, "host-sync-in-step")
        assert names(found) == ["host-sync-in-step"]
        assert "no justification" in found[0].message


# -------------------------------------------------------- CLI / tree

class TestCli:
    def test_exit_codes_and_json(self, tmp_path, capsys):
        bad = tmp_path / "bad.py"
        bad.write_text(textwrap.dedent("""
            import os, jax

            @jax.jit
            def f(x):
                return os.getenv("MODE"), x
        """))
        assert main([str(bad), "--format", "json"]) == 1
        payload = json.loads(capsys.readouterr().out)
        assert payload and payload[0]["rule"] == "env-read-in-trace"
        # the machine-readable record contract the CI inline-annotation
        # step consumes: exactly file/line/col/rule/message per finding
        for record in payload:
            assert set(record) == {"file", "line", "col", "rule",
                                   "message"}
            assert record["file"] == str(bad)
            assert isinstance(record["line"], int) and record["line"] > 0

        good = tmp_path / "good.py"
        good.write_text("x = 1\n")
        assert main([str(good)]) == 0
        assert "clean" in capsys.readouterr().out

    def test_json_format_carries_concurrency_findings(self, tmp_path,
                                                      capsys):
        racy = tmp_path / "racy.py"
        racy.write_text(textwrap.dedent("""
            import threading

            class S:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._buf = []
                    self._t = threading.Thread(target=self._run)

                def put(self, x):
                    self._buf.append(x)

                def _run(self):
                    self._buf.pop()
        """))
        assert main([str(racy), "--format", "json"]) == 1
        payload = json.loads(capsys.readouterr().out)
        assert [r["rule"] for r in payload] == ["unguarded-shared-field"]

    def test_json_format_carries_sharding_findings(self, tmp_path,
                                                   capsys):
        src = tmp_path / "shardy.py"
        src.write_text(textwrap.dedent("""
            import jax
            import numpy as np
            from jax.sharding import Mesh, PartitionSpec as P

            mesh = Mesh(np.array(jax.devices()), ("data",))

            def f(x):
                return x * 2

            g = jax.shard_map(f, mesh=mesh, in_specs=(P("model"),),
                              out_specs=P("data"))

            @jax.jit
            def train_step(s, b):
                return s

            def run(s, b):  # graftlint: hot-step
                s = train_step(s, b)
                return float(s)
        """))
        assert main([str(src), "--format", "json"]) == 1
        payload = json.loads(capsys.readouterr().out)
        rules = {r["rule"] for r in payload}
        # the new rule ids ride the same machine-readable record
        # contract the CI inline-annotation step consumes
        assert {"spec-mesh-mismatch", "host-sync-in-step"} <= rules
        for record in payload:
            assert set(record) == {"file", "line", "col", "rule",
                                   "message"}

    def test_changed_only_skips_unchanged_files(self, tmp_path, capsys):
        f = tmp_path / "mod.py"
        f.write_text("x = 1\n")
        state = tmp_path / "state.json"
        argv = [str(tmp_path), "--changed-only",
                "--state-file", str(state)]
        assert main(argv) == 0
        assert "1 file(s)" in capsys.readouterr().out
        # untouched on disk: the second run never re-lints
        assert main(argv) == 0
        assert "0 changed file(s)" in capsys.readouterr().out
        # an edit invalidates exactly the (path, mtime, size) record
        f.write_text("yy = 22\n")
        assert main(argv) == 0
        assert "1 file(s)" in capsys.readouterr().out

    def test_changed_only_keeps_flagged_files_dirty(self, tmp_path,
                                                    capsys):
        bad = tmp_path / "bad.py"
        bad.write_text(textwrap.dedent("""
            import os, jax

            @jax.jit
            def f(x):
                return os.getenv("MODE"), x
        """))
        state = tmp_path / "state.json"
        argv = [str(tmp_path), "--changed-only",
                "--state-file", str(state)]
        assert main(argv) == 1
        capsys.readouterr()
        # a file WITH findings must re-lint next run even when its
        # signature is unchanged — only clean files are recorded
        assert main(argv) == 1
        assert "env-read-in-trace" in capsys.readouterr().out

    def test_timings_flag_prints_per_rule_table(self, tmp_path, capsys):
        good = tmp_path / "good.py"
        good.write_text("x = 1\n")
        assert main([str(good), "--timings"]) == 0
        out = capsys.readouterr().out
        assert "timing:" in out
        assert "env-read-in-trace" in out       # per-rule rows

    def test_ast_cache_parses_each_file_once_across_runs(self, tmp_path):
        f = tmp_path / "mod.py"
        f.write_text("x = 1\n")
        lint_paths([str(f)])
        assert run_stats["parse_count"] == 1    # one parse, all rules
        assert run_stats["cache_hits"] == 0
        lint_paths([str(f)])                    # unchanged: free
        assert run_stats["parse_count"] == 0
        assert run_stats["cache_hits"] == 1
        f.write_text("yy = 22\n")               # edited: reparses
        lint_paths([str(f)])
        assert run_stats["parse_count"] == 1
        assert run_stats["cache_hits"] == 0

    def test_run_stats_reset_per_run_for_every_entry_point(self, tmp_path):
        # regression: lint_path/lint_source accumulated into run_stats
        # without resetting, so a long-lived caller (editor
        # integration) read mixed-run numbers — "stats of the most
        # recent lint run" is the documented contract
        f = tmp_path / "mod.py"
        f.write_text("x = 1\n")
        lint_path(str(f))
        first = run_stats["parse_count"] + run_stats["cache_hits"]
        assert first == 1
        lint_path(str(f))                       # NOT 2: reset, then 1
        assert run_stats["parse_count"] + run_stats["cache_hits"] == 1
        lint_source("x = 1\n")
        assert run_stats["parse_count"] == 1    # this run's parse only

    def test_unknown_rule_and_missing_path_are_errors(self, capsys):
        assert main(["--select", "no-such-rule", "."]) == 2
        assert main(["/no/such/path.py"]) == 2

    def test_list_rules(self, capsys):
        assert main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        assert "env-read-in-trace" in out
        assert "jit-missing-donate" in out


def test_repo_tree_is_clean_within_budget():
    """The CI gate, in-process: apex_tpu/tools/examples lint clean —
    with the concurrency pass enabled — and the full-tree run stays
    inside its wall budget (the per-file AST cache means every rule
    *and* the whole-program pass share one parse per file; measured
    ~4s on the dev box, budget leaves CI headroom)."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    targets = [os.path.join(root, d)
               for d in ("apex_tpu", "tools", "examples")]
    findings = lint_paths(targets)
    assert findings == [], "\n".join(f.render() for f in findings)
    assert run_stats["files"] >= 100            # the tree, not a stub
    assert run_stats["total_s"] < 60.0, run_stats
    # one parse per file, shared by all ~13 rules (pre-cache, each
    # rule re-parsed every file)
    assert run_stats["parse_count"] + run_stats["cache_hits"] \
        == run_stats["files"]
    # the concurrency pass actually ran on the tree, and its shared
    # analysis is charged to its own --timings row (not to whichever
    # of the four rules happened to trigger the memoization first)
    assert "unguarded-shared-field" in run_stats["rules_s"]
    assert run_stats["rules_s"].get("concurrency-pass", 0.0) > 0.0
    # same contract for the precision pass: the dtype-flow analysis
    # ran, charged to its own `precision-pass` row, and its five rules
    # are registered against the tree
    assert "bf16-unsafe-reduction" in run_stats["rules_s"]
    assert run_stats["rules_s"].get("precision-pass", 0.0) > 0.0
    # ... and the sharding pass (ISSUE-16): ran tree-wide, billed to
    # its own `sharding-pass` row, with the full run — all four
    # passes — inside the 20s acceptance budget (measured ~7s)
    assert "host-sync-in-step" in run_stats["rules_s"]
    assert run_stats["rules_s"].get("sharding-pass", 0.0) > 0.0
    assert run_stats["total_s"] < 20.0, run_stats
