"""graftlint rule fixtures — one flagged and one clean source per rule,
plus suppression/trace-inference/CLI coverage and the gate that the
repo's own tree stays clean (the CI job's in-process twin).

Pure AST work, no jax needed — but the shared conftest imports jax, so
these run inside the normal hermetic suite.
"""

import json
import os
import textwrap

import pytest

from tools.graftlint.core import all_rules, lint_paths, lint_source, main


def lint(src, rule=None):
    """Findings for dedented ``src``, optionally one rule only."""
    return lint_source(textwrap.dedent(src), "<fixture>",
                       select=[rule] if rule else None)


def names(findings):
    return [f.rule for f in findings]


def test_registry_has_at_least_eight_rules():
    rules = all_rules()
    assert len(rules) >= 8
    for name, rule in rules.items():
        assert rule.name == name and rule.summary


# ----------------------------------------------------- rule fixtures

class TestEnvReadInTrace:
    RULE = "env-read-in-trace"

    def test_flagged_inside_jitted_function(self):
        found = lint("""
            import os, jax

            @jax.jit
            def step(x):
                mode = os.environ.get("APEX_TPU_DECODE_ATTN", "auto")
                return x if mode == "einsum" else -x
        """, self.RULE)
        assert names(found) == [self.RULE]

    def test_flagged_inside_module_call(self):
        found = lint("""
            import os
            import flax.linen as nn

            class Attn(nn.Module):
                def __call__(self, x):
                    if os.getenv("FLAG"):
                        return x
                    return -x
        """, self.RULE)
        assert names(found) == [self.RULE]

    def test_module_level_read_near_trace_paths_is_advisory(self):
        found = lint("""
            import os, jax

            DEBUG = os.environ.get("DEBUG", "0")

            @jax.jit
            def f(x):
                return x
        """, self.RULE)
        assert names(found) == [self.RULE]
        assert "captured at import time" in found[0].message

    def test_clean_untraced_helper(self):
        assert lint("""
            import os

            def configure():
                return os.environ.get("HOME", "/")
        """, self.RULE) == []


class TestTracedBranch:
    RULE = "traced-branch"

    def test_flagged_if_on_traced_value(self):
        found = lint("""
            import jax
            import jax.numpy as jnp

            @jax.jit
            def f(x):
                y = jnp.sum(x)
                if y > 0:
                    return y
                return -y
        """, self.RULE)
        assert names(found) == [self.RULE]

    def test_flagged_while_on_traced_value(self):
        found = lint("""
            import jax

            @jax.jit
            def f(x):
                while x.sum() > 1:
                    x = x / 2
                return x
        """, self.RULE)
        assert names(found) == [self.RULE]

    def test_flagged_branch_inside_nested_loss_fn_closure(self):
        # the canonical jit'd train_step with an inner loss_fn closing
        # over the batch — the nested def is part of the same trace
        found = lint("""
            import jax
            import jax.numpy as jnp

            @jax.jit
            def train_step(state, batch):
                def loss_fn(p):
                    if batch.sum() > 0:
                        return jnp.mean(p * batch)
                    return jnp.mean(p)
                return jax.grad(loss_fn)(state)
        """, self.RULE)
        assert names(found) == [self.RULE]

    def test_nested_def_params_are_tainted(self):
        found = lint("""
            import jax

            @jax.jit
            def f(x):
                def inner(y):
                    if y > 0:
                        return y
                    return -y
                return inner(x)
        """, self.RULE)
        assert names(found) == [self.RULE]

    def test_clean_config_typed_param_branch(self):
        # *Config-typed params are hashable static config: branching
        # on their fields specializes the trace on purpose
        assert lint("""
            import flax.linen as nn

            def norm(cfg: TransformerConfig, name: str):
                class Norm(nn.Module):
                    def __call__(self, x):
                        if cfg.norm == "rmsnorm":
                            return x * cfg.eps
                        return x
                return Norm(name=name)

            class Block(nn.Module):
                def __call__(self, x):
                    return norm(self.cfg, "pre")(x)
        """, self.RULE) == []

    def test_clean_annotated_static_flag_closure(self):
        # an unannotated closure flag would over-taint; `causal: bool`
        # marks it static for the whole nested trace
        assert lint("""
            import jax
            from jax import lax

            def accum(q, axis: str, causal: bool, scale: float):
                def tick(carry, t):
                    if causal:
                        carry = carry * scale
                    return carry, None
                return lax.scan(tick, q, None, length=4)
        """, self.RULE) == []

    def test_clean_shape_branch_and_none_check(self):
        assert lint("""
            import jax

            @jax.jit
            def f(x, mask=None):
                if x.shape[0] > 128:
                    x = x[:128]
                if mask is not None:
                    x = x * mask
                return x
        """, self.RULE) == []


class TestJitUnhashableDefault:
    RULE = "jit-unhashable-default"

    def test_flagged_dict_default(self):
        found = lint("""
            import jax

            @jax.jit
            def f(x, opts={}):
                return x
        """, self.RULE)
        assert names(found) == [self.RULE]

    def test_flagged_call_site_list_default(self):
        found = lint("""
            import jax

            def f(x, axes=[0, 1]):
                return x

            g = jax.jit(f)
        """, self.RULE)
        assert names(found) == [self.RULE]

    def test_clean_hashable_defaults(self):
        assert lint("""
            import jax

            @jax.jit
            def f(x, axes=(0, 1), scale=1.0, mask=None):
                return x
        """, self.RULE) == []


class TestJitMissingDonate:
    RULE = "jit-missing-donate"

    def test_flagged_train_step_without_donate(self):
        found = lint("""
            import jax

            @jax.jit
            def train_step(state, batch):
                return state
        """, self.RULE)
        assert names(found) == [self.RULE]

    def test_clean_with_donate_argnums(self):
        assert lint("""
            import functools
            import jax

            @functools.partial(jax.jit, donate_argnums=(0,))
            def train_step(state, batch):
                return state
        """, self.RULE) == []

    def test_clean_no_state_shaped_params(self):
        assert lint("""
            import jax

            @jax.jit
            def forward(params, x):
                return x
        """, self.RULE) == []


class TestLruCacheHazard:
    RULE = "lru-cache-hazard"

    def test_flagged_env_read_under_lru_cache(self):
        found = lint("""
            import functools, os

            @functools.lru_cache(maxsize=8)
            def compiled_run(n):
                return os.environ.get("APEX_TPU_DECODE_ATTN", "auto"), n
        """, self.RULE)
        assert names(found) == [self.RULE]

    def test_flagged_unhashable_default(self):
        found = lint("""
            import functools

            @functools.lru_cache
            def build(shape=[1, 2]):
                return tuple(shape)
        """, self.RULE)
        assert names(found) == [self.RULE]

    def test_clean_hashable_pure(self):
        assert lint("""
            import functools

            @functools.lru_cache(maxsize=None)
            def build(shape=(1, 2), dtype="f32"):
                return shape, dtype
        """, self.RULE) == []


class TestTimeInTrace:
    RULE = "time-in-trace"

    def test_flagged_wallclock_and_np_random(self):
        found = lint("""
            import time, jax
            import numpy as np

            @jax.jit
            def f(x):
                t0 = time.time()
                noise = np.random.randn(4)
                return x + noise, t0
        """, self.RULE)
        assert names(found) == [self.RULE, self.RULE]

    def test_clean_timing_outside_jit(self):
        assert lint("""
            import time, jax

            @jax.jit
            def f(x):
                return x * 2

            def bench(x):
                t0 = time.time()
                f(x)
                return time.time() - t0
        """, self.RULE) == []


class TestHostSyncInTrace:
    RULE = "host-sync-in-trace"

    def test_flagged_item_and_float(self):
        found = lint("""
            import jax

            @jax.jit
            def f(x):
                s = x.sum()
                return float(s), s.item()
        """, self.RULE)
        assert names(found) == [self.RULE, self.RULE]

    def test_flagged_float_inside_nested_loss_fn(self):
        found = lint("""
            import jax
            import jax.numpy as jnp

            @jax.jit
            def train_step(params, batch):
                def loss_fn(p):
                    return float(jnp.mean(p * batch))
                return jax.grad(loss_fn)(params)
        """, self.RULE)
        assert names(found) == [self.RULE]

    def test_clean_static_conversions(self):
        assert lint("""
            import jax

            @jax.jit
            def f(x):
                n = int(x.shape[0])
                return x[:n]
        """, self.RULE) == []


class TestPrintInTrace:
    RULE = "print-in-trace"

    def test_flagged_print_of_tracer(self):
        found = lint("""
            import jax

            @jax.jit
            def f(x):
                print(x)
                return x
        """, self.RULE)
        assert names(found) == [self.RULE]

    def test_flagged_fstring_of_tracer(self):
        found = lint("""
            import jax

            @jax.jit
            def f(x):
                msg = f"value = {x.sum()}"
                return x
        """, self.RULE)
        assert names(found) == [self.RULE]

    def test_flagged_fstring_in_nested_closure_and_no_duplicates(self):
        found = lint("""
            import jax

            @jax.jit
            def train_step(params, batch):
                def loss_fn(p):
                    msg = f"loss input {batch.sum()}"
                    return (p * batch).sum()
                return jax.grad(loss_fn)(params)
        """, self.RULE)
        assert names(found) == [self.RULE]   # exactly once

    def test_clean_fstring_in_raise_and_outside_print(self):
        assert lint("""
            import jax

            @jax.jit
            def f(x):
                if x.ndim != 2:
                    raise ValueError(f"need 2D, got {x.ndim}, {x}")
                return x

            def report(y):
                print(f"loss = {y}")
        """, self.RULE) == []


class TestMutableGlobalInTrace:
    RULE = "mutable-global-in-trace"

    def test_flagged_module_list_append(self):
        found = lint("""
            import jax

            HISTORY = []

            @jax.jit
            def f(x):
                HISTORY.append(1)
                return x
        """, self.RULE)
        assert names(found) == [self.RULE]

    def test_flagged_global_rebind(self):
        found = lint("""
            import jax

            STEPS = []

            @jax.jit
            def f(x):
                global STEPS
                STEPS = [x]
                return x
        """, self.RULE)
        assert names(found) == [self.RULE]

    def test_clean_local_container(self):
        assert lint("""
            import jax

            @jax.jit
            def f(x):
                parts = []
                parts.append(x)
                return parts[0]
        """, self.RULE) == []


# ----------------------------------------------------- suppressions

FLAGGED = """
    import os, jax

    @jax.jit
    def f(x):
        mode = os.getenv("MODE"){trailer}
        return x
"""


class TestSuppression:
    def test_trailing_disable(self):
        src = FLAGGED.format(
            trailer="  # graftlint: disable=env-read-in-trace")
        assert lint(src, "env-read-in-trace") == []

    def test_standalone_disable_covers_next_line(self):
        found = lint("""
            import os, jax

            @jax.jit
            def f(x):
                # graftlint: disable=env-read-in-trace
                mode = os.getenv("MODE")
                return x
        """, "env-read-in-trace")
        assert found == []

    def test_file_wide_disable(self):
        found = lint("""
            # graftlint: disable-file=env-read-in-trace
            import os, jax

            @jax.jit
            def f(x):
                mode = os.getenv("MODE")
                return x
        """, "env-read-in-trace")
        assert found == []

    def test_disable_all(self):
        src = FLAGGED.format(trailer="  # graftlint: disable=all")
        assert lint(src, "env-read-in-trace") == []

    def test_trailing_commentary_does_not_break_suppression(self):
        # the documented style: a suppression plus the why
        src = FLAGGED.format(
            trailer="  # graftlint: disable=env-read-in-trace — "
                    "host-only value, never traced")
        assert lint(src, "env-read-in-trace") == []

    def test_wrong_rule_does_not_suppress(self):
        src = FLAGGED.format(
            trailer="  # graftlint: disable=traced-branch")
        assert names(lint(src, "env-read-in-trace")) \
            == ["env-read-in-trace"]

    def test_not_traced_mark_opts_out(self):
        found = lint("""
            import os
            import flax.linen as nn

            class M(nn.Module):
                def __call__(self, x):  # graftlint: not-traced
                    return os.getenv("HOME"), x
        """, "env-read-in-trace")
        assert found == []

    def test_traced_mark_opts_in(self):
        found = lint("""
            import os

            def helper(x):  # graftlint: traced
                return os.getenv("HOME"), x
        """, "env-read-in-trace")
        assert names(found) == ["env-read-in-trace"]


# ------------------------------------------- trace-path inference

class TestTraceInference:
    def test_scan_callee_is_traced(self):
        found = lint("""
            import os
            from jax import lax

            def body(carry, x):
                flag = os.getenv("FLAG")
                return carry, x

            def run(xs):
                return lax.scan(body, 0, xs)
        """, "env-read-in-trace")
        assert names(found) == ["env-read-in-trace"]

    def test_transitive_same_file_helper(self):
        found = lint("""
            import os, jax

            def helper(x):
                return os.getenv("MODE"), x

            @jax.jit
            def f(x):
                return helper(x)
        """, "env-read-in-trace")
        assert names(found) == ["env-read-in-trace"]

    def test_fori_loop_body_is_traced(self):
        found = lint("""
            import os
            from jax import lax

            def body(i, x):
                return x * (2 if os.getenv("FLAG") else 3)

            def run(x):
                return lax.fori_loop(0, 10, body, x)
        """, "env-read-in-trace")
        assert names(found) == ["env-read-in-trace"]

    def test_cond_false_branch_is_traced(self):
        found = lint("""
            import os
            from jax import lax

            def on_false(x):
                return x * len(os.environ["SCALE"])

            def run(pred, x):
                return lax.cond(pred, lambda x: x, on_false, x)
        """, "env-read-in-trace")
        assert names(found) == ["env-read-in-trace"]

    def test_switch_branches_are_traced(self):
        found = lint("""
            import os
            from jax import lax

            def branch_b(x):
                return x + len(os.environ["B"])

            def run(i, x):
                return lax.switch(i, [lambda x: x, branch_b], x)
        """, "env-read-in-trace")
        # branch passed inside a list literal is not resolvable by
        # name-position — but passed positionally it must be
        found2 = lint("""
            import os
            from jax import lax

            def branch_b(x):
                return x + len(os.environ["B"])

            def run(i, x):
                return lax.switch(i, branch_b, x)
        """, "env-read-in-trace")
        assert names(found2) == ["env-read-in-trace"]

    def test_cond_predicate_name_is_not_marked_traced(self):
        # `flag` at cond's args[0] is the predicate, not a callable:
        # a same-named def must NOT become a trace path
        found = lint("""
            import os
            from jax import lax

            def flag():
                return os.getenv("FLAG") == "1"

            def run(flag, x):
                return lax.cond(flag, lambda x: x, lambda x: -x, x)
        """, "env-read-in-trace")
        assert found == []

    def test_kwargs_catchall_is_tainted(self):
        found = lint("""
            import jax

            @jax.jit
            def f(x, **kw):
                if kw["mask"].sum() > 0:
                    return x
                return -x
        """, "traced-branch")
        assert names(found) == ["traced-branch"]

    def test_parse_error_is_reported_not_raised(self):
        found = lint_source("def f(:\n", "<bad>")
        assert names(found) == ["parse-error"]

    def test_no_duplicate_findings_for_repeated_jit_sites(self):
        found = lint("""
            import jax

            def train_step(state, batch):
                return state

            a = jax.jit(train_step)
            b = jax.jit(train_step)
        """, "jit-missing-donate")
        assert names(found) == ["jit-missing-donate"]


# -------------------------------------------------------- CLI / tree

class TestCli:
    def test_exit_codes_and_json(self, tmp_path, capsys):
        bad = tmp_path / "bad.py"
        bad.write_text(textwrap.dedent("""
            import os, jax

            @jax.jit
            def f(x):
                return os.getenv("MODE"), x
        """))
        assert main([str(bad), "--format", "json"]) == 1
        payload = json.loads(capsys.readouterr().out)
        assert payload and payload[0]["rule"] == "env-read-in-trace"

        good = tmp_path / "good.py"
        good.write_text("x = 1\n")
        assert main([str(good)]) == 0
        assert "clean" in capsys.readouterr().out

    def test_unknown_rule_and_missing_path_are_errors(self, capsys):
        assert main(["--select", "no-such-rule", "."]) == 2
        assert main(["/no/such/path.py"]) == 2

    def test_list_rules(self, capsys):
        assert main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        assert "env-read-in-trace" in out
        assert "jit-missing-donate" in out


def test_repo_tree_is_clean():
    """The CI gate, in-process: apex_tpu/tools/examples lint clean."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    targets = [os.path.join(root, d)
               for d in ("apex_tpu", "tools", "examples")]
    findings = lint_paths(targets)
    assert findings == [], "\n".join(f.render() for f in findings)
