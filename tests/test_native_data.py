"""Native _apex_C packer + prefetch loader (host runtime pieces).

Native tests are skip-guarded on the built extension, mirroring the
reference's contrib import-try pattern (SURVEY.md §4)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu import native
from apex_tpu.data import PrefetchLoader, prefetch_to_device
from apex_tpu.core import mesh as mesh_lib


class TestNativeFlatten:
    def test_fallback_roundtrip(self, rng):
        arrs = [rng.normal(size=(4, 3)).astype(np.float32),
                np.arange(7, dtype=np.int64)]
        # force the numpy path regardless of build
        flat = np.concatenate([a.view(np.uint8).reshape(-1)
                               for a in arrs])
        out = native.unflatten_host_buffer(flat, arrs)
        for a, b in zip(arrs, out):
            np.testing.assert_array_equal(a, b)

    @pytest.mark.skipif(not native.HAVE_NATIVE,
                        reason="_apex_C not built")
    def test_native_roundtrip(self, rng):
        arrs = [rng.normal(size=(128, 64)).astype(np.float32),
                rng.integers(0, 100, size=(33,)).astype(np.int32),
                np.empty((0,), np.float64)]
        flat = native.flatten_host_buffers(arrs)
        assert flat.nbytes == sum(a.nbytes for a in arrs)
        out = native.unflatten_host_buffer(flat, arrs)
        for a, b in zip(arrs, out):
            np.testing.assert_array_equal(a, b)

    @pytest.mark.skipif(not native.HAVE_NATIVE,
                        reason="_apex_C not built")
    def test_native_size_mismatch_raises(self):
        with pytest.raises(ValueError):
            native.unflatten_host_buffer(
                np.zeros(10, np.uint8), [np.zeros(3, np.uint8)])


class TestPrefetch:
    def test_order_and_values(self, rng):
        batches = [{"x": np.full((4,), i, np.float32)} for i in range(5)]
        out = list(PrefetchLoader(batches, buffer_size=2))
        assert len(out) == 5
        for i, b in enumerate(out):
            assert isinstance(b["x"], jax.Array)
            np.testing.assert_array_equal(np.asarray(b["x"]), i)

    def test_sharded_prefetch(self, rng):
        m = mesh_lib.initialize_mesh(data_parallel_size=8)
        try:
            sharding = NamedSharding(m, P("data"))
            batches = [np.ones((16, 2), np.float32) * i
                       for i in range(3)]
            out = list(prefetch_to_device(batches, 2, sharding=sharding))
            assert out[1].sharding.spec == P("data")
            np.testing.assert_array_equal(np.asarray(out[2]), 2.0)
        finally:
            mesh_lib.destroy_mesh()

    def test_transform_and_error_propagation(self):
        def gen():
            yield np.ones((2,))
            raise RuntimeError("source died")

        it = PrefetchLoader(gen(), transform=lambda b: b * 2)
        got = []
        with pytest.raises(RuntimeError, match="source died"):
            for b in it:
                got.append(np.asarray(b))
        assert len(got) == 1 and got[0][0] == 2.0

    def test_early_exit_no_thread_leak(self):
        import threading, time
        before = {t.name for t in threading.enumerate()}
        it = iter(PrefetchLoader(
            (np.full((2,), i, np.float32) for i in range(1000)),
            buffer_size=2))
        next(it)
        it.close()
        deadline = time.monotonic() + 6.0
        while time.monotonic() < deadline:
            alive = [t for t in threading.enumerate()
                     if t.name == "apex-tpu-prefetch" and t.is_alive()]
            if not alive:
                break
            time.sleep(0.05)
        assert not alive, "prefetch worker leaked after early exit"

    def test_source_closed_on_early_exit(self):
        closed = []

        def gen():
            try:
                for i in range(100):
                    yield np.full((2,), i, np.float32)
            finally:
                closed.append(True)

        it = iter(PrefetchLoader(gen(), buffer_size=1))
        next(it)
        it.close()
        assert closed == [True]
