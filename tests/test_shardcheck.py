"""Unit tier for the runtime placement sanitizer
(``apex_tpu.utils.shardcheck``) — the dynamic twin of graftlint's
sharding pass, the way ``tests/test_numcheck.py`` pins the numerics
sanitizer: instrument idempotence, strict mode in both directions (a
planted declared-vs-actual breach is recorded strict-only), the
``APEX_TPU_SHARDCHECK`` env gate, the declared-vs-actual positive
mismatch on the 8-device CPU mesh the conftest forces, transfer-event
attribution through the ``jax.monitoring`` seam, and the
tensor-parallel paged-engine integration (the committed pool/state
placement survives warmup → admit → step → release under the
recorder, with the ``trace_counts`` diagnostics still readable
through the proxies).

Every test runs under an autouse reset + ``uninstrument()`` so the
process-wide listener and wrapped steps never leak into the suite.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu.utils import shardcheck


@pytest.fixture(autouse=True)
def _isolated():
    shardcheck.reset()
    yield
    shardcheck.uninstrument()
    shardcheck.reset()


@pytest.fixture(scope="module")
def mesh8():
    devs = jax.devices()
    assert len(devs) >= 8, "conftest forces 8 virtual CPU devices"
    return Mesh(np.array(devs[:8]), ("data",))


def _sharded_step(mesh8, out_spec):
    """A jitted step whose output placement is pinned to ``out_spec``
    — the ground truth the declared tree is checked against."""
    return jax.jit(lambda x: x * 2.0,
                   out_shardings=NamedSharding(mesh8, out_spec))


# --------------------------------------------------------------------- #
# env gate
# --------------------------------------------------------------------- #
class TestEnvGate:
    def test_env_strict_reads_the_chaos_smoke_setting(self, monkeypatch):
        monkeypatch.delenv("APEX_TPU_SHARDCHECK", raising=False)
        assert not shardcheck.env_strict()
        monkeypatch.setenv("APEX_TPU_SHARDCHECK", "strict")
        assert shardcheck.env_strict()
        monkeypatch.setenv("APEX_TPU_SHARDCHECK", "observe")
        assert not shardcheck.env_strict()

    def test_wrap_step_follows_env_default(self, monkeypatch, mesh8):
        monkeypatch.setenv("APEX_TPU_SHARDCHECK", "strict")
        step = shardcheck.wrap_step(
            _sharded_step(mesh8, P()),            # actually replicated
            declared=NamedSharding(mesh8, P("data")),   # claims sharded
            mesh=mesh8, name="env_step")
        step(jnp.arange(8.0))
        assert shardcheck.reports(), \
            "strict env + declared/actual mismatch must record"


# --------------------------------------------------------------------- #
# declared vs actual on the 8-device mesh
# --------------------------------------------------------------------- #
class TestDeclaredVsActual:
    def test_matching_placement_is_clean(self, mesh8):
        step = shardcheck.wrap_step(
            _sharded_step(mesh8, P("data")),
            declared=NamedSharding(mesh8, P("data")),
            mesh=mesh8, name="good_step", strict=True)
        step(jnp.arange(8.0))
        shardcheck.assert_clean()
        stats = shardcheck.site_shardings()["good_step"]
        assert stats["calls"] == 1
        assert stats["checked"] == 1
        assert stats["mismatched"] == 0

    def test_mismatch_recorded_in_strict(self, mesh8):
        step = shardcheck.wrap_step(
            _sharded_step(mesh8, P()),            # replication fallback
            declared=NamedSharding(mesh8, P("data")),
            mesh=mesh8, name="bad_step", strict=True)
        step(jnp.arange(8.0))
        found = shardcheck.reports()
        assert len(found) == 1
        assert "bad_step" in found[0]
        assert "placement mismatch" in found[0]
        with pytest.raises(shardcheck.ShardCheckError):
            shardcheck.assert_clean()
        # one report per distinct site, not per step
        step(jnp.arange(8.0))
        assert len(shardcheck.reports()) == 1

    def test_mismatch_observed_only_when_not_strict(self, mesh8,
                                                    monkeypatch):
        monkeypatch.delenv("APEX_TPU_SHARDCHECK", raising=False)
        step = shardcheck.wrap_step(
            _sharded_step(mesh8, P()),
            declared=NamedSharding(mesh8, P("data")),
            mesh=mesh8, name="observed_step", strict=False)
        step(jnp.arange(8.0))
        stats = shardcheck.site_shardings()["observed_step"]
        assert stats["mismatched"] == 1       # counted ...
        shardcheck.assert_clean()             # ... but never a violation

    def test_bare_partition_specs_resolve_against_mesh(self, mesh8):
        step = shardcheck.wrap_step(
            _sharded_step(mesh8, P("data")),
            declared=P("data"), mesh=mesh8,
            name="spec_step", strict=True)
        step(jnp.arange(8.0))
        shardcheck.assert_clean()
        assert shardcheck.site_shardings()["spec_step"]["checked"] == 1

    def test_declared_tree_covers_tuple_outputs(self, mesh8):
        base = jax.jit(
            lambda x: (x * 2.0, jnp.sum(x)),
            out_shardings=(NamedSharding(mesh8, P("data")),
                           NamedSharding(mesh8, P())))
        step = shardcheck.wrap_step(
            base,
            declared=(NamedSharding(mesh8, P("data")),
                      NamedSharding(mesh8, P())),
            mesh=mesh8, name="tuple_step", strict=True)
        step(jnp.arange(8.0))
        shardcheck.assert_clean()
        assert shardcheck.site_shardings()["tuple_step"]["checked"] == 2


# --------------------------------------------------------------------- #
# transfer accounting (the jax.monitoring seam; CPU zero-copies defeat
# jax.transfer_guard, so tests inject synthetic events)
# --------------------------------------------------------------------- #
class TestTransferAccounting:
    def test_in_window_transfer_is_a_strict_violation(self, mesh8):
        def leaky(x):
            jax.monitoring.record_event(
                "/shardcheck_test/transfer_d2h", num_bytes=64)
            return x * 2.0

        step = shardcheck.wrap_step(
            leaky, declared=None, mesh=mesh8,
            name="leaky_step", strict=True)
        step(jnp.arange(8.0))
        s = shardcheck.summary()
        assert s["d2h_events"] == 1
        assert s["d2h_bytes"] == 64
        assert s["transfer_sites"] == {"leaky_step": 1}
        found = shardcheck.reports()
        assert len(found) == 1
        assert "leaky_step" in found[0]

    def test_out_of_window_transfer_is_counted_not_flagged(self):
        shardcheck.instrument(object(), strict=True)  # listener only
        jax.monitoring.record_event(
            "/shardcheck_test/transfer_d2h", num_bytes=32)
        s = shardcheck.summary()
        assert s["d2h_events"] == 1
        assert s["d2h_bytes"] == 32
        assert s["transfer_sites"] == {}
        shardcheck.assert_clean()

    def test_unrelated_events_are_ignored(self):
        shardcheck.instrument(object(), strict=True)
        jax.monitoring.record_event("/shardcheck_test/compile_time")
        assert shardcheck.summary()["d2h_events"] == 0


# --------------------------------------------------------------------- #
# the jax_compat check_vma -> check_rep shim (ISSUE-16 satellite): the
# runtime twin of graftlint's unreplicated-out-spec rule must surface
# the same-shaped trace-time error on jax 0.4.37 (where the kwarg is
# check_rep) as on current jax (check_vma) — every call site in the
# repo writes the current spelling through the shim
# --------------------------------------------------------------------- #
class TestCheckVmaShim:
    def test_divergent_return_with_replicated_out_spec_raises(
            self, mesh8):
        from apex_tpu.utils import jax_compat

        def body(x):
            return x * 2.0        # shard-divergent, no reduction

        sm = jax_compat.shard_map(
            body, mesh=mesh8, in_specs=(P("data"),), out_specs=P(),
            check_vma=True)
        with pytest.raises(ValueError) as exc:
            jax.jit(sm)(jnp.arange(8.0))
        # the rule-3 shape, pinned across jax versions: the error
        # names out_specs and the replication contract it violates
        msg = str(exc.value)
        assert "out_specs" in msg
        assert "replicat" in msg.lower()

    def test_reduction_on_the_return_path_passes_the_check(
            self, mesh8):
        from apex_tpu.utils import jax_compat

        def body(x):
            return jax.lax.psum(x, "data")

        sm = jax_compat.shard_map(
            body, mesh=mesh8, in_specs=(P("data"),), out_specs=P(),
            check_vma=True)
        out = jax.jit(sm)(jnp.arange(8.0))
        # per-shard (1,) inputs, psum'd and replicated: global (1,)
        np.testing.assert_allclose(np.asarray(out), [28.0])

    def test_check_vma_false_disables_the_check(self, mesh8):
        # the chaos-soak spelling: check_vma=False must map onto the
        # old check_rep=False rather than raise on 0.4.37
        from apex_tpu.utils import jax_compat

        def body(x):
            return x * 2.0

        sm = jax_compat.shard_map(
            body, mesh=mesh8, in_specs=(P("data"),), out_specs=P("data"),
            check_vma=False)
        out = jax.jit(sm)(jnp.arange(8.0))
        assert out.shape == (8,)


# --------------------------------------------------------------------- #
# instrument mechanics on the TP paged engine (8-device CPU mesh)
# --------------------------------------------------------------------- #
@pytest.fixture(scope="module")
def tp_engine():
    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.serving import PagedEngine, tp_mesh

    cfg = GPTConfig.tiny(position_embedding="learned", scan_layers=True)
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))
    return PagedEngine(model, {"params": params["params"]},
                       mesh=tp_mesh(2), max_slots=2, block_size=8,
                       prefill_chunk=4)


class TestEngineInstrument:
    def test_idempotent_and_restorable(self, tp_engine):
        inner = tp_engine.__dict__["_decode"]
        shardcheck.instrument(tp_engine, strict=True)
        once = tp_engine.__dict__["_decode"]
        shardcheck.instrument(tp_engine, strict=True)   # no-op
        assert tp_engine.__dict__["_decode"] is once
        assert once is not inner
        shardcheck.uninstrument()
        assert tp_engine.__dict__["_decode"] is inner

    def test_committed_placement_holds_through_the_step_cycle(
            self, tp_engine):
        shardcheck.instrument(tp_engine, strict=True)
        tp_engine.warmup()
        tp_engine.admit(0, np.arange(5, dtype=np.int32),
                        max_new_tokens=3)
        for _ in range(4):
            tp_engine.step()
        tp_engine.release(0)
        # the diagnostics proxy through the wrappers untouched
        assert tp_engine.trace_counts == {"decode_step": 1,
                                          "prefill_step": 1,
                                          "admit": 1, "release": 1}
        sites = shardcheck.site_shardings()
        decode = sites["PagedEngine._decode"]
        assert decode["calls"] >= 1
        assert decode["checked"] > 0          # pool + state leaves
        assert decode["mismatched"] == 0
        assert sites["PagedEngine._admit"]["checked"] > 0
        shardcheck.assert_clean()
