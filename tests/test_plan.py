"""apex_tpu.plan — the AMP-style auto-parallelism planner (ISSUE 15).

The claims under test, on the 8-virtual-device CPU mesh:

- **dedup**: ``bench_configs`` imports the lifted cost formulas back
  from ``apex_tpu.plan.costs`` (same function objects — zero drift
  possible), the model-block key sets are frozen at their recorded
  r01–r05 spellings, and the blocks recorded in ``BENCH_CONFIGS.json``
  recompute byte-identically.
- **enumeration**: tensor degrees pass the GQA ``tp_head_shards``
  gate, ring/ulysses appear only where the model supports them, ZeRO
  stages only where there is a data axis to shard over.
- **feasibility**: per-chip HBM pruning orders DP vs ZeRO-2 the way
  the measured ``bert_o1_zero`` rows did, and an
  infeasible-everywhere config raises the loud per-layout diagnostic.
- **prediction fidelity**: the planner's score ordering reproduces
  the measured relative ordering of the recorded bench rows —
  dense-vs-paged decode, dp-vs-zero2 hbm_peak, 1×M-vs-M×1 per-chip
  tokens/s, and the occupancy-sweep curve shape.
- **the CI smoke**: planning a tiny GPT for 8 CPU devices returns a
  feasible mesh + specs, and the emitted ZeRO placement equals the
  library's own ``zero_shardings``.
- **autotune seam**: kernel winners are adopted under the PER-SHARD
  kv-head key; a miss falls back to the analytic estimate with a
  counted ``plan.autotune_miss`` — never a full-head-count alias,
  never a zero score (the PR-12 rule, negative-tested).
"""

import json
import os

import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import apex_tpu
from apex_tpu import amp
from apex_tpu.models import BertConfig, GPTConfig, LlamaConfig
from apex_tpu.models.resnet import ResNetConfig
from apex_tpu.optim import fused_adam
from apex_tpu.parallel import zero_shardings, zero_state_specs
from apex_tpu.plan import (
    HardwareSpec,
    InfeasibleError,
    Layout,
    costs,
    emit_plan,
    enumerate_layouts,
    generic_profile,
    memory_model,
    profile_of,
    score_layout,
    xla_cost_seed,
)
from apex_tpu.plan.score import autotuned_paged_layout
from apex_tpu.utils.metrics import counters

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
N = 8


# --------------------------------------------------------------- dedup

class TestCostModelDedup:
    """Satellite 1: ONE implementation, imported back by the bench."""

    def test_bench_imports_back_same_objects(self):
        import bench_configs

        assert bench_configs._resnet_traffic_model \
            is costs.resnet_traffic_model
        assert bench_configs._ddp_bytes_on_wire \
            is costs.ddp_bytes_on_wire
        assert bench_configs._zero_bytes_on_wire \
            is costs.zero_bytes_on_wire
        assert bench_configs._serving_traffic_model \
            is costs.serving_traffic_model

    def test_model_block_keys_frozen(self):
        # the r01–r05 emission key sets, pinned: a renamed column
        # would silently orphan every recorded row
        assert tuple(costs.resnet_traffic_model(128, 224)) \
            == ("floor", "bn_real")
        assert tuple(costs.resnet_traffic_model(
            128, 224, fused_bn=True)) \
            == ("floor", "bn_real", "bn_fused_kernel")
        assert tuple(costs.ddp_bytes_on_wire(1000, 8)) == (
            "replicas", "grad_elements", "wire_bytes_per_step_fp32",
            "wire_bytes_per_step_bf16", "wire_bytes_per_step_int8",
            "int8_wire_reduction_vs_fp32")
        assert tuple(costs.zero_bytes_on_wire(1000, 8)) == (
            "shards", "stage", "reduce_dtype", "grad_elements",
            "wire_bytes_reduce_scatter", "wire_bytes_param_all_gather",
            "wire_bytes_per_step_zero",
            "wire_bytes_per_step_dp_fp32_allreduce",
            "wire_reduction_vs_dp", "model_state_bytes_per_chip_dp",
            "model_state_bytes_per_chip_zero",
            "state_bytes_saved_per_chip", "state_savings_frac")
        tm = costs.serving_traffic_model(
            num_layers=2, kv_heads=2, head_dim=64, max_seq_len=256,
            live_tokens=40, slots=4, block_size=8)
        assert tuple(tm) == (
            "tp", "ici_bytes_per_step_per_chip", "ici_bytes_per_step",
            "paged_kv_read_bytes_per_step_per_chip",
            "dense_kv_read_bytes_per_step",
            "paged_kv_read_bytes_per_step", "dense_pool_bytes",
            "paged_pool_tokens", "live_tokens", "block_size",
            "shared_prefix_tokens", "paged_live_pool_tokens_unshared",
            "paged_live_pool_tokens_shared",
            "paged_live_pool_bytes_unshared",
            "paged_live_pool_bytes_shared",
            "shared_capacity_multiplier")

    def test_recorded_bench_blocks_recompute_byte_identical(self):
        # every model block the recorded rows carry recomputes
        # byte-for-byte from the lifted implementation
        path = os.path.join(_REPO, "BENCH_CONFIGS.json")
        recorded = json.load(open(path))
        checked = 0
        for leg in ("resnet50_o1", "resnet50_syncbn"):
            row = recorded[leg]
            block = row.get("analytic_traffic_bytes")
            if not block:
                continue
            got = costs.resnet_traffic_model(
                int(row["batch"]), 224,
                fused_bn="bn_fused_kernel" in block)
            assert json.dumps(got, sort_keys=True) \
                == json.dumps(block, sort_keys=True), leg
            checked += 1
        assert checked >= 2      # the rows exist — not vacuous

    def test_bench_configs_no_longer_defines_the_bodies(self):
        src = open(os.path.join(_REPO, "bench_configs.py")).read()
        for name in ("_resnet_traffic_model", "_ddp_bytes_on_wire",
                     "_zero_bytes_on_wire", "_serving_traffic_model"):
            assert f"def {name}(" not in src, name


# --------------------------------------------------------- enumeration

class TestEnumeration:
    def test_serve_tp_through_gqa_gate(self):
        # llama_1b: 16 q heads over 4 kv heads — tp ∈ divisors of 4
        prof = profile_of(LlamaConfig.llama_1b())
        layouts = enumerate_layouts(prof, N, "serve")
        tps = sorted(l.tp for l in layouts)
        assert tps == [1, 2, 4]
        assert all(l.dp * l.tp == N for l in layouts)

    def test_train_zero_needs_a_data_axis(self):
        prof = profile_of(GPTConfig.tiny())
        layouts = enumerate_layouts(prof, 4, "train")
        assert any(l.zero_stage == 2 and l.reduce_dtype == "int8"
                   for l in layouts)
        assert all(l.zero_stage == 0
                   for l in layouts if l.dp == 1)

    def test_context_axis_only_where_supported(self):
        # BERT is bidirectional: no ring/ulysses, no serving
        bert = profile_of(BertConfig.bert_large())
        assert all(l.cp == 1
                   for l in enumerate_layouts(bert, N, "train"))
        with pytest.raises(ValueError, match="causal"):
            enumerate_layouts(bert, N, "serve")
        # llama supports both at cp=2 (2048 % 2 == 0, 16 heads)
        llama = profile_of(LlamaConfig.llama_1b())
        attns = {(l.cp, l.attn)
                 for l in enumerate_layouts(llama, N, "train")}
        assert (2, "ring") in attns and (2, "ulysses") in attns
        # review regression: the ring gate divides the seq the caller
        # actually trains at, not the config's max_seq_len — at an
        # odd seq no ring layout may be emitted (ulysses, gated on
        # heads, survives)
        odd = {(l.cp, l.attn)
               for l in enumerate_layouts(llama, N, "train", seq=49)}
        assert not any(a == "ring" for _cp, a in odd)
        assert (2, "ulysses") in odd

    def test_resnet_and_generic_are_dp_only(self):
        for prof in (profile_of(ResNetConfig()),
                     generic_profile(10_000)):
            layouts = enumerate_layouts(prof, N, "train")
            assert layouts
            assert all(l.tp == 1 and l.cp == 1 for l in layouts)

    def test_profiles_count_params_sanely(self):
        # analytic counts within 2% of the measured bench rows
        assert abs(profile_of(LlamaConfig.llama_1b()).n_params
                   - 1_032_931_328) / 1_032_931_328 < 0.02
        assert abs(profile_of(GPTConfig.gpt2_1p3b()).n_params
                   - 1.316e9) / 1.316e9 < 0.02
        assert abs(profile_of(ResNetConfig()).n_params
                   - 25.6e6) / 25.6e6 < 0.02

    def test_moe_experts_counted_not_dense(self):
        # review regression: profiling 8 experts as one dense MLP
        # would pass the feasibility gate for layouts that OOM on
        # chip — mixtral_8x7b must land near its real 46.7B, and the
        # MoE profile must dominate its dense twin by ~the expert
        # multiplier on the MLP term
        moe = profile_of(LlamaConfig.mixtral_8x7b())
        dense = profile_of(LlamaConfig.mistral_7b())
        assert abs(moe.n_params - 46.7e9) / 46.7e9 < 0.02
        assert moe.n_params > 6 * dense.n_params


# --------------------------------------------------------- feasibility

class TestFeasibility:
    def test_context_axis_shards_the_residency(self):
        # review regression: the logits CE residual (like the
        # activations) shards its sequence axis on context — a cp
        # layout must not be charged the full-sequence residual
        prof = profile_of(LlamaConfig.llama_1b())
        solo = memory_model(prof, Layout(dp=1), batch_per_chip=1)
        cp2 = memory_model(prof, Layout(dp=1, cp=2, attn="ring"),
                           batch_per_chip=1)
        assert cp2["logits"] == solo["logits"] // 2
        assert cp2["activations"] == solo["activations"] // 2

    def test_zero2_frees_per_chip_hbm(self):
        # the measured bert_o1_zero ordering: ZeRO-2 residency <
        # replicated DP at equal batch, by ~the optimizer state
        prof = profile_of(BertConfig.bert_large())
        dp = memory_model(prof, Layout(dp=N), batch_per_chip=2)
        z2 = memory_model(prof, Layout(dp=N, zero_stage=2,
                                       reduce_dtype="int8"),
                          batch_per_chip=2)
        assert z2["total"] < dp["total"]
        saved = dp["optimizer_state"] - z2["optimizer_state"]
        # ~ (12 - 12/n) B/param of the fp32 master+moments move off
        assert saved > 0.8 * 12 * prof.n_params * (1 - 1 / N)

    def test_zero2_reclaimed_hbm_buys_batch(self):
        # the zero2_grown row's mechanism: at the DP layout's HBM
        # budget, the ZeRO-2 layout fits a strictly larger per-chip
        # batch
        prof = profile_of(BertConfig.bert_large())

        def max_batch(layout, budget):
            b = 0
            while memory_model(prof, layout,
                               batch_per_chip=b + 1)["total"] <= budget:
                b += 1
                if b > 512:
                    break
            return b

        budget = memory_model(prof, Layout(dp=N),
                              batch_per_chip=8)["total"]
        assert max_batch(Layout(dp=N, zero_stage=2), budget) \
            > max_batch(Layout(dp=N), budget)

    def test_infeasible_everywhere_is_loud(self):
        with pytest.raises(InfeasibleError) as ei:
            apex_tpu.plan(LlamaConfig.llama2_7b(), devices=1,
                          hw=HardwareSpec(hbm_bytes=8e9))
        msg = str(ei.value)
        assert "binding" in msg
        assert "optimizer_state" in msg or "activations" in msg
        assert "8.0 GB/chip" in msg
        assert ei.value.pruned     # the per-layout breakdown rides it

    def test_serve_infeasible_names_the_kv_pool(self):
        prof = profile_of(LlamaConfig.llama_1b())
        with pytest.raises(InfeasibleError) as ei:
            apex_tpu.plan(prof, devices=1, objective="serve",
                          slots=64, hw=HardwareSpec(hbm_bytes=3e9))
        assert "kv_pool" in str(ei.value) \
            or "params" in str(ei.value)


# ------------------------------------------------- prediction fidelity

class TestPredictionFidelity:
    """Satellite 3: the planner's score ordering reproduces the
    measured relative ordering of the recorded bench rows."""

    @pytest.fixture(scope="class")
    def recorded(self):
        return json.load(open(os.path.join(_REPO,
                                           "BENCH_CONFIGS.json")))

    def test_dense_vs_paged_read_ordering(self, recorded):
        # the recorded decode A/B: the live-read (blocked) step beats
        # the full-slab (einsum) read at S=2048, and the gap GROWS at
        # S=8192 — the live-independence the dense model encodes
        rows = recorded["decode"]["rows"]
        meas = {}
        for s in (2048, 8192):
            meas[s] = (rows[f"b8_S{s}"]["decode_tokens_per_sec"]
                       / rows[f"b8_S{s}_einsum"]
                       ["decode_tokens_per_sec"])
        assert meas[8192] > meas[2048] > 1.0     # the recorded facts

        cfg = LlamaConfig.llama_1b()
        prof = profile_of(cfg)
        pred = {}
        for s in (2048, 8192):
            tm = costs.serving_traffic_model(
                num_layers=cfg.num_layers, kv_heads=cfg.kv_heads,
                head_dim=cfg.head_dim, max_seq_len=s,
                live_tokens=1024 + 32, slots=8, block_size=16,
                dtype_bytes=2)
            params = 2 * prof.n_params
            pred[s] = ((params + tm["dense_kv_read_bytes_per_step"])
                       / (params
                          + tm["paged_kv_read_bytes_per_step"]))
        assert pred[8192] > pred[2048] > 1.0
        # dense reads are live-independent: the dense column does not
        # move when live tokens do, the paged one scales ~linearly
        tm_lo = costs.serving_traffic_model(
            num_layers=2, kv_heads=4, head_dim=64, max_seq_len=2048,
            live_tokens=64, slots=8, block_size=16)
        tm_hi = costs.serving_traffic_model(
            num_layers=2, kv_heads=4, head_dim=64, max_seq_len=2048,
            live_tokens=256, slots=8, block_size=16)
        assert tm_lo["dense_kv_read_bytes_per_step"] \
            == tm_hi["dense_kv_read_bytes_per_step"]
        assert tm_hi["paged_kv_read_bytes_per_step"] \
            == 4 * tm_lo["paged_kv_read_bytes_per_step"]

    def test_dp_vs_zero2_hbm_peak_ordering(self):
        # the recorded bert_o1_zero rows: hbm_peak dropped 56% at
        # equal batch (1.566 GB → 688 MB at the tiny preset) — the
        # planner's residency must order the same way, stage by stage
        prof = profile_of(BertConfig.bert_large())
        totals = [memory_model(prof, lay, batch_per_chip=2)["total"]
                  for lay in (Layout(dp=N),
                              Layout(dp=N, zero_stage=1),
                              Layout(dp=N, zero_stage=2))]
        assert totals[0] > totals[1] >= totals[2]
        zm = costs.zero_bytes_on_wire(prof.n_params, N)
        assert zm["state_savings_frac"] > 0.5    # the 56%-class drop

    def test_1xM_vs_Mx1_per_chip_ordering(self):
        # the tp_serving protocol: at equal chip count the M×1 fleet
        # is the per-chip throughput ceiling (zero ICI); the 1×M TP
        # row pays the ICI column for capacity
        prof = profile_of(LlamaConfig.llama_1b())
        fleet = score_layout(prof, Layout(objective="serve", dp=2),
                             slots=4)
        tp = score_layout(prof, Layout(objective="serve", dp=1, tp=2),
                          slots=4)
        assert fleet["value"] >= tp["value"]
        assert fleet["t_ici_s"] == 0.0 and tp["t_ici_s"] > 0.0
        assert tp["traffic_model"]["ici_bytes_per_step_per_chip"] > 0
        # ...and the TP row is the only one that shrinks per-chip
        # residency — the capacity it buys
        assert tp["hbm_residency"]["params"] \
            < fleet["hbm_residency"]["params"]

    def test_occupancy_sweep_curve_shape(self, recorded=None):
        # the serving_decode sweep (docs/perf_serving.md): 1×/2×/4×
        # the slots in one budget measured 1 / 2.25 / 3.96× tokens/s —
        # increasing, sublinear at the top, ×4 under 2× the ×2 gain
        prof = profile_of(LlamaConfig.llama_1b())
        tps = {m: score_layout(
            prof, Layout(objective="serve", dp=1),
            slots=2 * m, live_tokens=144)["value"]
            for m in (1, 2, 4)}
        assert tps[4] > tps[2] > tps[1]
        sp2, sp4 = tps[2] / tps[1], tps[4] / tps[1]
        assert 1.0 < sp2 < 2.0 and sp2 < sp4 < 4.0
        assert sp4 < 2 * sp2        # measured: 3.96 < 2 × 2.25
        # per-slot efficiency decays with occupancy (the amortized
        # param stream saturates) — the measured curve's concavity
        assert tps[4] / 8 < tps[2] / 4


# ------------------------------------------------------- the CI smoke

class TestPlanSmoke:
    """Satellite 5: the tier-1 gate — plan a tiny GPT for the 8-device
    CPU mesh, feasible + emitted specs place like ``zero_shardings``."""

    def test_tiny_gpt_plans_feasibly(self):
        p = apex_tpu.plan(GPTConfig.tiny(), devices=N)
        assert p.objective == "train"
        assert p.layout.chips == N
        assert p.mesh is not None and p.mesh.devices.size == N
        assert p.score["value"] > 0
        assert p.alternatives    # the A/B is inspectable
        assert "samples/sec/chip" in p.describe()

    @pytest.mark.parametrize("ndev", [1, N])
    @pytest.mark.parametrize("cfg_fn", [
        GPTConfig.tiny, GPTConfig.gpt2_1p3b, BertConfig.bert_large,
        LlamaConfig.llama_1b, ResNetConfig],
        ids=["gpt_tiny", "gpt2_1p3b", "bert_large", "llama_1b",
             "resnet50"])
    def test_model_zoo_plans_on_cpu_meshes(self, cfg_fn, ndev):
        # the acceptance bar: a feasible Mesh + specs for the zoo on
        # 1- and 8-device CPU meshes at the default HBM budget
        p = apex_tpu.plan(cfg_fn(), devices=ndev)
        assert p.mesh.devices.size == ndev
        assert p.score["value"] > 0
        assert p.score["hbm_residency"]["total"] \
            <= apex_tpu.plan.DEFAULT_HW.hbm_bytes

    def test_emitted_zero_specs_place_like_zero_shardings(self):
        p = emit_plan(
            GPTConfig.tiny(), Layout(dp=N, zero_stage=2),
            jax.devices()[:N],
            score_layout(GPTConfig.tiny(), Layout(dp=N, zero_stage=2)),
            [])
        assert p.zero is not None and p.zero.axis_size == N
        params = {"w": jnp.ones((16, 33)), "b": jnp.zeros((33,))}
        state = amp.initialize(lambda pr, x: x @ pr["w"] + pr["b"],
                               params, fused_adam(1e-3),
                               opt_level="O2",
                               half_dtype=jnp.bfloat16, zero=p.zero)
        got = p.state_shardings(state)
        want = zero_shardings(state, mesh=p.mesh)
        assert jax.tree.all(jax.tree.map(lambda a, b: a == b, got,
                                         want))
        specs = p.state_specs(state)
        assert jax.tree.all(jax.tree.map(lambda a, b: a == b, specs,
                                         zero_state_specs(state)))
        # the master shards really land on the data axis
        flat = jax.tree.leaves(
            specs.opt_state,
            is_leaf=lambda x: isinstance(x, P))
        assert any(s and s[0] == "data" for s in flat)

    def test_tp_plan_emits_gspmd_layer_annotations(self):
        p = emit_plan(GPTConfig.tiny(), Layout(dp=4, tp=2),
                      jax.devices()[:N],
                      score_layout(GPTConfig.tiny(),
                                   Layout(dp=4, tp=2)), [])
        assert p.param_specs is not None
        flat = jax.tree.leaves(
            p.param_specs, is_leaf=lambda x: isinstance(x, P))
        assert any("tensor" in tuple(s) for s in flat
                   if isinstance(s, P))
        assert dict(p.mesh.shape)["tensor"] == 2
        assert p.data_spec == P("data")

    def test_serve_plan_splits_the_chips(self):
        p = apex_tpu.plan(GPTConfig.tiny(), devices=N,
                          objective="serve")
        assert p.replicas * p.tp == N
        assert p.engine_kwargs["kv_cache"] == "paged"
        flat = [d for devs in p.replica_devices for d in devs]
        assert sorted(flat, key=str) \
            == sorted(jax.devices()[:N], key=str)
        if p.tp > 1:
            assert len(p.replica_meshes()) == p.replicas

    def test_impossible_slo_is_loud(self):
        with pytest.raises(ValueError, match="ttft_ms"):
            apex_tpu.plan(LlamaConfig.llama_1b(), devices=N,
                          objective="serve", slo={"ttft_ms": 1e-9})

    def test_entry_point_validation(self):
        with pytest.raises(ValueError, match="objective"):
            apex_tpu.plan(GPTConfig.tiny(), devices=2,
                          objective="infer")
        with pytest.raises(ValueError, match="device"):
            apex_tpu.plan(GPTConfig.tiny(), devices=10**6)
        with pytest.raises(TypeError, match="profile"):
            apex_tpu.plan(object(), devices=2)
        # objective-mismatched knobs are loud, not silently ignored
        with pytest.raises(ValueError, match="cost_seed"):
            apex_tpu.plan(GPTConfig.tiny(), devices=2,
                          objective="serve",
                          cost_seed={"flops": 1.0,
                                     "bytes_accessed": 1.0})
        with pytest.raises(ValueError, match="slo"):
            apex_tpu.plan(GPTConfig.tiny(), devices=2,
                          objective="train", slo={"ttft_ms": 100})
        # ...and so is a typoed SLO key (it must not yield a plan
        # that merely LOOKS SLO-checked)
        with pytest.raises(ValueError, match="ttft_p50_ms"):
            apex_tpu.plan(GPTConfig.tiny(), devices=2,
                          objective="serve",
                          slo={"ttft_p50_ms": 200})

    def test_bare_profile_plans_for_train(self):
        # review regression: a ModelProfile is a documented plan()
        # input — emit must not try to trace a flax module out of it
        prof = profile_of(GPTConfig.tiny())
        p = apex_tpu.plan(prof, devices=N)
        assert p.param_specs is None     # geometry only, no module
        assert p.mesh.devices.size == N and p.score["value"] > 0

    def test_module_is_callable_and_a_package(self):
        # the ROADMAP-4 spelling apex_tpu.plan(...) AND the package
        # surface apex_tpu.plan.costs both work
        assert callable(apex_tpu.plan)
        assert apex_tpu.plan.costs.ddp_bytes_on_wire is \
            costs.ddp_bytes_on_wire


# ------------------------------------------------------- autotune seam

class TestAutotuneSeam:
    """Satellite 6: per-shard-keyed winners adopted; misses fall back
    analytic with a counted ``plan.autotune_miss`` — never 0."""

    @pytest.fixture
    def fresh_cache(self, tmp_path, monkeypatch):
        from apex_tpu.ops import autotune

        monkeypatch.setenv("APEX_TPU_AUTOTUNE_CACHE",
                           str(tmp_path / "autotune.json"))
        autotune.clear_cache()
        yield autotune
        autotune.clear_cache()

    def test_miss_counts_and_falls_back_analytic(self, fresh_cache):
        prof = profile_of(LlamaConfig.llama_1b())
        before = counters.get("plan.autotune_miss")
        tuned = autotuned_paged_layout(prof, tp=2)
        assert counters.get("plan.autotune_miss") == before + 1
        assert tuned == {"block_size": 16, "kv_dtype": None,
                         "autotuned": False}
        # ...and the score built on the fallback is a real number,
        # not the silent 0 the satellite forbids
        s = score_layout(prof, Layout(objective="serve", dp=1, tp=2),
                         slots=4)
        assert s["value"] > 0 and s["autotune"]["autotuned"] is False

    def test_per_shard_winner_adopted(self, fresh_cache):
        # bf16 inference config — the dtype the engine (and thus the
        # planner) keys the lookup on
        prof = profile_of(LlamaConfig.llama_1b(dtype=jnp.bfloat16))
        fresh_cache._store(
            fresh_cache._key("paged_attention_pair", prof.head_dim,
                             "bfloat16", kv_heads=2),
            [32, "int8"])
        before = counters.get("plan.autotune_miss")
        tuned = autotuned_paged_layout(prof, tp=2)   # shard width 2
        assert counters.get("plan.autotune_miss") == before
        assert tuned == {"block_size": 32, "kv_dtype": "int8",
                         "autotuned": True}
        s = score_layout(prof, Layout(objective="serve", dp=1, tp=2),
                         slots=4)
        assert s["value"] > 0
        assert s["autotune"]["autotuned"] is True
        assert s["traffic_model"]["block_size"] == 32
        assert s["traffic_model"]["kv_dtype"] == "int8"

    def test_fp16_config_keys_fp16_not_bf16(self, fresh_cache):
        # review regression: the cache dtype key is the EXACT config
        # dtype name (as PagedEngine keys it) — float16 shares bf16's
        # width but not its cache entry
        prof = profile_of(LlamaConfig.llama_1b(dtype=jnp.float16))
        assert prof.dtype_name == "float16"
        fresh_cache._store(
            fresh_cache._key("paged_attention_pair", prof.head_dim,
                             "float16", kv_heads=prof.kv_heads),
            [32, "int8"])
        tuned = autotuned_paged_layout(prof, tp=1)
        assert tuned == {"block_size": 32, "kv_dtype": "int8",
                         "autotuned": True}

    def test_full_head_count_winner_never_aliases(self, fresh_cache):
        # the PR-12 rule: an entry swept at FULL head count must not
        # be adopted by a tp plan querying its per-shard width — the
        # profile dtype matches the stored key exactly, so kv_heads is
        # the ONLY mismatched component (the aliasing under test)
        prof = profile_of(LlamaConfig.llama_1b(dtype=jnp.bfloat16))
        fresh_cache._store(
            fresh_cache._key("paged_attention_pair", prof.head_dim,
                             "bfloat16", kv_heads=prof.kv_heads),
            [64, "int8"])
        before = counters.get("plan.autotune_miss")
        tuned = autotuned_paged_layout(prof, tp=2)
        assert counters.get("plan.autotune_miss") == before + 1
        assert tuned["autotuned"] is False
        assert tuned["block_size"] == 16     # analytic default, not 64

    def test_xla_cost_seed_anchors_the_roofline(self):
        @jax.jit
        def f(x):
            return (x @ x).sum()

        compiled = f.lower(jnp.ones((64, 64))).compile()
        seed = xla_cost_seed(compiled)
        if seed is None:
            pytest.skip("backend offers no cost analysis")
        assert seed["flops"] > 0
        s = score_layout(profile_of(GPTConfig.tiny()), Layout(dp=1),
                         cost_seed=seed)
        assert s["cost_seed"] is seed and s["value"] > 0
        # review regression: the seed describes the single-chip step,
        # so a model-sharded layout's per-chip roofline must shrink by
        # its cp×tp degree — an un-rescaled seed would make every
        # layout's roofline identical and degenerate the ranking
        tp2 = score_layout(profile_of(GPTConfig.tiny()),
                           Layout(dp=1, tp=2), cost_seed=seed)
        assert tp2["t_mxu_s"] == pytest.approx(s["t_mxu_s"] / 2)
        assert tp2["t_hbm_s"] == pytest.approx(s["t_hbm_s"] / 2)

    def test_serve_feasibility_judged_on_tuned_pool(self, fresh_cache):
        # review regression: feasibility must adopt the SAME autotuned
        # (block_size, kv_dtype) the score and engine kwargs do — a
        # model whose bf16 pool busts the budget but whose tuned int8
        # pool fits must plan, not raise InfeasibleError
        cfg = LlamaConfig.llama_1b(dtype=jnp.bfloat16)
        prof = profile_of(cfg)
        fresh_cache._store(
            fresh_cache._key("paged_attention_pair", prof.head_dim,
                             "bfloat16", kv_heads=prof.kv_heads),
            [16, "int8"])
        bf16 = memory_model(prof, Layout(objective="serve", dp=1),
                            slots=8)["total"]
        int8 = memory_model(prof, Layout(objective="serve", dp=1),
                            slots=8, kv_dtype="int8")["total"]
        budget = (bf16 + int8) / 2          # between the two pools
        p = apex_tpu.plan(cfg, devices=1, objective="serve",
                          slots=8, hw=HardwareSpec(hbm_bytes=budget))
        assert p.engine_kwargs["kv_dtype"] == "int8"
        assert p.score["hbm_residency"]["total"] <= budget


# ----------------------------------------------------------- generics

class TestGenericProfile:
    def test_generic_plan_matches_example_usage(self):
        # the --plan auto path of examples/simple/distributed.py
        p = apex_tpu.plan(generic_profile(2305), devices=N)
        assert p.layout.dp == N and p.layout.tp == 1
        assert p.zero is None or p.zero.axis_size == N

    def test_resnet_zoo_plans(self):
        p = apex_tpu.plan(ResNetConfig(), devices=N,
                          batch_per_chip=32)
        assert p.layout.dp == N
        assert p.score["hbm_residency"]["activations"] > 0


class TestPipelinePlanning:
    """ISSUE-20: the ``pipe`` axis end-to-end through the planner —
    enumeration gates, per-stage residency, the bubble + boundary-wire
    score terms, and the emitted Plan driving an actual 1F1B run."""

    def _prof(self, layers=4):
        # the tiny residual-MLP stack the pipeline unit tests train:
        # 4 × (16·16 + 16 + 16·16) = 2112 fp32 params
        return generic_profile(2112, dtype_bytes=4, num_layers=layers)

    def test_pipe_degrees_enumerate_behind_the_gates(self):
        pipes = {l.pipe for l in
                 enumerate_layouts(self._prof(8), 8, "train")}
        assert pipes == {1, 2, 4, 8}
        # layer-divisibility gate: 6 layers admit only pipe ∈ {1, 2}
        assert {l.pipe for l in
                enumerate_layouts(self._prof(6), 8, "train")} \
            == {1, 2}
        # microbatch gate: pipe <= m
        assert {l.pipe for l in
                enumerate_layouts(self._prof(8), 8, "train",
                                  microbatches=2)} == {1, 2}
        # a profile with no layer count cannot pipeline
        flat = generic_profile(2112, dtype_bytes=4)
        assert {l.pipe for l in
                enumerate_layouts(flat, 8, "train")} == {1}

    def test_per_stage_residency_divides_state(self):
        prof = self._prof()
        dp = memory_model(prof, Layout(dp=8), batch_per_chip=4)
        p4 = memory_model(prof, Layout(dp=2, pipe=4),
                          batch_per_chip=4, microbatches=4)
        # each stage holds 1/pipe of params / optimizer / grads
        assert p4["params"] == dp["params"] / 4
        assert p4["optimizer_state"] == dp["optimizer_state"] / 4
        assert p4["gradients"] == dp["gradients"] / 4

    def test_pipeline_costs_match_the_schedule_quantities(self):
        from apex_tpu.parallel import pipeline as pl

        pc = costs.pipeline_costs(4, 8, microbatch_tokens=128,
                                  hidden_size=64, dtype_bytes=2)
        assert pc["bubble_fraction"] == \
            pytest.approx(pl.bubble_fraction(4, 8))
        assert pc["schedule_ticks"] == pl.schedule_ticks(4, 8)
        assert pc["live_microbatches"] == pl.live_microbatches(4)
        # boundary traffic: 2(p-1) activation hops per microbatch,
        # none at all without a pipe split
        payload = 128 * 64 * 2
        assert pc["boundary_bytes_per_step"] == 2 * 3 * 8 * payload
        assert costs.pipeline_costs(
            1, 8, microbatch_tokens=128, hidden_size=64,
            dtype_bytes=2)["boundary_bytes_per_step"] == 0

    def test_bubble_term_monotone_in_microbatches(self):
        # more microbatches amortize the (p-1)/m bubble: the score
        # must strictly improve, and the scorecard carries the
        # pipeline cost block for inspection
        prof = self._prof(8)
        lay = Layout(dp=2, pipe=4)
        s8 = score_layout(prof, lay, batch_per_chip=4, microbatches=8)
        s16 = score_layout(prof, lay, batch_per_chip=4,
                           microbatches=16)
        assert s8["bubble_fraction"] == pytest.approx(3 / 8)
        assert s16["bubble_fraction"] == pytest.approx(3 / 16)
        assert s16["value"] > s8["value"]
        assert s8["pipeline"]["stages"] == 4
        assert s8["microbatches"] == 8

    def test_tight_hbm_keeps_only_pipe_layouts_and_plan_trains(self):
        """The acceptance scenario: at a budget every dp/ZeRO layout
        busts (the best pipe-free residency is 12672 B here), the
        planner returns a pipelined layout — and adopting the emitted
        Plan (mesh, ZeroConfig, stage assignment, placement) actually
        trains."""
        import numpy as np

        from apex_tpu.parallel import pipeline as pl

        prof = self._prof()
        p = apex_tpu.plan(prof, devices=8,
                          hw=HardwareSpec(hbm_bytes=9000),
                          batch_per_chip=4, microbatches=4)
        assert p.layout.pipe > 1
        assert all(s["layout"].pipe > 1 for s in p.alternatives)
        assert p.microbatches == 4
        per = 4 // p.layout.pipe
        assert p.stage_assignment == [
            (s * per, (s + 1) * per) for s in range(p.layout.pipe)]
        assert p.mesh.shape["pipe"] == p.layout.pipe
        assert p.zero is not None and p.zero.axis_size == p.layout.dp

        # ---- adopt the plan: stage_split by its assignment, its
        # ZeroConfig, its mesh, its placement — and train
        hid, layers, mb = 16, 4, 2
        dp, pp, m = p.layout.dp, p.layout.pipe, p.microbatches
        r = np.random.default_rng(0)
        params = {"stages": (
            jnp.asarray(r.normal(size=(layers, hid, hid)) * 0.3,
                        jnp.float32),
            jnp.asarray(r.normal(size=(layers, hid)) * 0.1,
                        jnp.float32),
            jnp.asarray(r.normal(size=(layers, hid, hid)) * 0.3,
                        jnp.float32),
        )}
        x = jnp.asarray(r.normal(size=(dp * m, mb, hid)), jnp.float32)
        y = jnp.asarray(r.normal(size=(dp * m, mb, hid)), jnp.float32)

        staged = {"stages": pl.stage_split(params["stages"], pp)}
        state = amp.initialize(None, staged, fused_adam(1e-2),
                               opt_level="O0", zero=p.zero)
        state = pl.stage_local_zero(state, num_stages=pp)
        state = jax.device_put(state, p.state_shardings(state))

        def layer_apply(xx, args):
            w1, b1, w2 = args
            return xx + jnp.tanh(xx @ w1 + b1) @ w2, None

        def stage_fn(sp, xx):
            xx, _ = jax.lax.scan(layer_apply, xx, sp)
            return xx

        def body(state, mbs, labels):
            def loss_fn(out, i):
                yl = jax.lax.dynamic_index_in_dim(labels, i, 0,
                                                  keepdims=False)
                return jnp.mean((out - yl) ** 2)

            loss, grads = pl.run_1f1b(stage_fn, loss_fn,
                                      state.params["stages"], mbs)
            grads = pl.sync_grad_overflow({"stages": grads})
            new_state, _ = state.apply_gradients(grads=grads)
            return new_state, jax.lax.pmean(loss, "data")

        # the emitted mesh carries every library axis (degenerate
        # ones at size 1) — wrap_pipeline_step folds those into the
        # manual set, so this exercises the planner-mesh path
        step = pl.wrap_pipeline_step(
            body, state=state, mesh=p.mesh,
            batch_specs=(p.data_spec, p.data_spec))
        losses = []
        for _ in range(5):
            state, loss = step(state, x, y)
            losses.append(float(loss))
        assert np.all(np.isfinite(losses))
        assert losses[-1] < losses[0]


class TestCalibrate:
    """plan.calibrate — the measured HardwareSpec (ISSUE-20
    satellite): off-accelerator identity, forced sweeps, the
    ``hardware=`` alias."""

    def test_cpu_host_returns_defaults_untouched(self):
        from apex_tpu.plan import DEFAULT_HW, calibrate

        # a host-emulated "peak" would poison the feasibility gate:
        # off-accelerator the bench-constant defaults come back AS-IS
        assert calibrate() is DEFAULT_HW

    def test_forced_sweeps_measure_this_host(self):
        from apex_tpu.plan import DEFAULT_HW, calibrate

        hw = calibrate(force=True, matmul_n=64, copy_mbytes=1,
                       psum_mbytes=1, iters=1)
        assert hw is not DEFAULT_HW
        assert hw.peak_tflops > 0
        assert hw.peak_hbm_gbs > 0
        assert hw.peak_ici_gbs > 0      # 8 virtual devices: a wire
        # ... and they are measurements, not the bench constants
        assert hw.peak_tflops != DEFAULT_HW.peak_tflops

    def test_single_device_keeps_the_ici_default(self):
        from apex_tpu.plan import DEFAULT_HW, calibrate

        hw = calibrate(jax.devices()[:1], force=True, matmul_n=32,
                       copy_mbytes=1, iters=1)
        assert hw.peak_ici_gbs == DEFAULT_HW.peak_ici_gbs

    def test_hardware_alias_plans_and_double_spec_errors(self):
        from apex_tpu.plan import DEFAULT_HW, calibrate

        prof = generic_profile(2112, dtype_bytes=4, num_layers=4)
        p = apex_tpu.plan(prof, devices=8, hardware=calibrate())
        assert p.score["value"] > 0
        with pytest.raises(ValueError, match="not both"):
            apex_tpu.plan(prof, devices=8, hw=DEFAULT_HW,
                          hardware=DEFAULT_HW)
