"""Example-script smoke tests (subprocess, CPU mesh).

Round-2 verdict weak #7: the ``--data FILE.npz`` branch of the imagenet
example had never executed (no dataset in this environment) — here a
tiny synthetic npz exercises the real-data code path end to end.  The
``--pp`` pipelined mode of transformer_tp (build_model + spmd_pipeline)
gets the same treatment.
"""

import os
import re
import subprocess
import sys

import numpy as np
import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_example(script, args, timeout=900):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8"
                        ).strip()
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, os.path.join(_REPO, script), *args],
        env=env, capture_output=True, text=True, timeout=timeout)


_SAMPLE_NPZ = os.path.join(_REPO, "examples", "data",
                           "sample_imagenet.npz")


@pytest.mark.slow
class TestImagenetExample:
    # [slow: 3 subprocess train runs ≈ 200s — the --data loader branch
    # integration; the dcgan test below keeps a conv-example subprocess
    # in tier-1]
    def test_checked_in_shard_trains(self):
        # the in-repo uint8 sample shard (examples/data, regenerable
        # via make_sample.py) through the real --data loader branch
        r = _run_example(
            "examples/imagenet/main_amp.py",
            ["--data", _SAMPLE_NPZ, "--arch", "resnet18",
             "--batch-size", "16", "--image-size", "32",
             "--steps", "3", "--opt-level", "O2"])
        assert r.returncode == 0, r.stderr[-2000:]
        # num_classes must have been derived from the npz labels, and
        # the printed losses must be finite
        losses = re.findall(r"loss (\d+\.\d+)", r.stdout)
        assert losses, r.stdout[-2000:]
        assert all(np.isfinite(float(l)) for l in losses)

    def test_npz_data_branch_trains(self, tmp_path, rng):
        # tiny class-separable float32 dataset through the same loader
        n, size, classes = 16, 32, 4
        labels = rng.integers(0, classes, size=(n,))
        protos = rng.normal(size=(classes, size, size, 3))
        images = (protos[labels]
                  + 0.3 * rng.normal(size=(n, size, size, 3)))
        path = tmp_path / "tiny.npz"
        np.savez(path, images=images.astype(np.float32),
                 labels=labels.astype(np.int64))

        r = _run_example(
            "examples/imagenet/main_amp.py",
            ["--data", str(path), "--arch", "resnet18",
             "--batch-size", "16", "--image-size", str(size),
             "--steps", "3", "--opt-level", "O2"])
        assert r.returncode == 0, r.stderr[-2000:]
        losses = re.findall(r"loss (\d+\.\d+)", r.stdout)
        assert losses, r.stdout[-2000:]
        assert all(np.isfinite(float(l)) for l in losses)

    def test_npz_num_classes_from_labels(self, tmp_path, rng):
        path = tmp_path / "two.npz"
        np.savez(path,
                 images=rng.normal(size=(8, 32, 32, 3)).astype(
                     np.float32),
                 labels=np.asarray([0, 1, 2, 0, 1, 2, 0, 6],
                                   np.int64))
        r = _run_example(
            "examples/imagenet/main_amp.py",
            ["--data", str(path), "--arch", "resnet18",
             "--batch-size", "8", "--image-size", "32",
             "--steps", "1"])
        assert r.returncode == 0, r.stderr[-2000:]


class TestDCGANExample:
    def test_checked_in_shard_real_branch(self):
        # the dcgan --data branch (real images as the D's positive
        # distribution) on the in-repo shard
        r = _run_example(
            "examples/dcgan/main_amp.py",
            ["--data", _SAMPLE_NPZ, "--batch-size", "16",
             "--steps", "2"])
        assert r.returncode == 0, r.stderr[-2000:]
        pairs = re.findall(r"G (\d+\.\d+)\s+D (\d+\.\d+)", r.stdout)
        assert len(pairs) == 2, r.stdout[-1000:]
        assert all(np.isfinite(float(g)) and np.isfinite(float(d))
                   for g, d in pairs)


class TestTransformerTPExample:
    def test_pp_mode(self):
        r = _run_example(
            "examples/transformer_tp.py",
            ["--tp", "2", "--pp", "2", "--dp", "2", "--steps", "2",
             "--batch-size", "4", "--seq-len", "32"])
        assert r.returncode == 0, r.stderr[-2000:]
        losses = re.findall(r"loss (\d+\.\d+)", r.stdout)
        assert len(losses) == 2, r.stdout[-1000:]
        assert all(np.isfinite(float(l)) for l in losses)

    def test_pp_rejects_bad_batch(self):
        r = _run_example(
            "examples/transformer_tp.py",
            ["--tp", "2", "--pp", "2", "--dp", "2",
             "--batch-size", "3", "--seq-len", "32"])
        assert r.returncode != 0
        assert "multiple of the microbatch" in (r.stderr + r.stdout)


class TestDistributedExample:
    def test_zero2_trains_sharded(self):
        # ISSUE-11 satellite: the --zero path stops hand-replicating
        # optimizer state — sharded masters/moments over the 8-device
        # 'data' axis, reduce-scatter grad sync, ResilientLoop intact
        r = _run_example("examples/simple/distributed.py",
                         ["--zero", "2", "--steps", "30"])
        assert r.returncode == 0, r.stderr[-2000:]
        assert "zero: stage 2 over 8-way 'data' axis" in r.stdout, \
            r.stdout[-2000:]
        # the printed state shard is a genuine 1/n slice
        assert "B/device (~1/8 of replicated)" in r.stdout
        losses = re.findall(r"loss (\d+\.\d+)", r.stdout)
        assert losses, r.stdout[-2000:]
        assert all(np.isfinite(float(l)) for l in losses)
        assert float(losses[-1]) < float(losses[0])

    def test_plan_auto_routes_layout(self):
        # ISSUE-15 satellite: --plan auto stops hand-picking the
        # layout — the ZeRO stage/wire come from apex_tpu.plan() over
        # a parameter-count profile; training must still converge on
        # the planned layout
        r = _run_example("examples/simple/distributed.py",
                         ["--plan", "auto", "--steps", "20"])
        assert r.returncode == 0, r.stderr[-2000:]
        assert "plan: auto -> dp=8" in r.stdout, r.stdout[-2000:]
        assert "alternatives scored" in r.stdout
        losses = re.findall(r"loss (\d+\.\d+)", r.stdout)
        assert losses, r.stdout[-2000:]
        assert float(losses[-1]) < float(losses[0])

    @pytest.mark.slow
    def test_plan_auto_yields_to_explicit_zero(self):
        # [slow: a second subprocess run of the example; the
        # explicit-flag precedence itself is argument plumbing — the
        # tier-1 smoke above keeps the planner path exercised]
        # explicit flags still win: --zero 1 pins the stage, the
        # planner is never consulted
        r = _run_example("examples/simple/distributed.py",
                         ["--plan", "auto", "--zero", "1",
                          "--steps", "12"])
        assert r.returncode == 0, r.stderr[-2000:]
        assert "plan: auto" not in r.stdout
        assert "zero: stage 1" in r.stdout, r.stdout[-2000:]

    @pytest.mark.slow
    def test_zero1_int8_wire_trains(self):
        # [slow: a second subprocess run of the same example; the
        # stage-1 and int8-wire semantics are tier-1-covered by
        # test_zero.py]
        r = _run_example("examples/simple/distributed.py",
                         ["--zero", "1", "--zero-int8",
                          "--steps", "30"])
        assert r.returncode == 0, r.stderr[-2000:]
        assert "zero: stage 1" in r.stdout and "int8" in r.stdout
        losses = re.findall(r"loss (\d+\.\d+)", r.stdout)
        assert losses and float(losses[-1]) < float(losses[0])

    @pytest.mark.slow
    def test_zero2_ckpt_resume(self, tmp_path):
        # [slow: two subprocess runs — kill-free resume of the SHARDED
        # state through the zero_shardings restore target; the
        # placement semantics are tier-1-covered by test_zero.py]
        d = str(tmp_path / "ckpts")
        r1 = _run_example("examples/simple/distributed.py",
                          ["--zero", "2", "--steps", "25",
                           "--ckpt-dir", d])
        assert r1.returncode == 0, r1.stderr[-2000:]
        r2 = _run_example("examples/simple/distributed.py",
                          ["--zero", "2", "--steps", "40",
                           "--ckpt-dir", d])
        assert r2.returncode == 0, r2.stderr[-2000:]
        m = re.search(r"resumed_from (\d+)", r2.stdout)
        assert m and int(m.group(1)) >= 20, r2.stdout[-2000:]


class TestServingDemoExample:
    def test_mixed_traffic_serves(self):
        r = _run_example("examples/serving_demo.py",
                         ["--requests", "5", "--max-slots", "2"])
        assert r.returncode == 0, r.stderr[-2000:]
        assert r.stdout.count("req ") == 5, r.stdout[-2000:]
        assert "done: 5 requests" in r.stdout, r.stdout[-2000:]
        # the metrics sink must have streamed at least one ordered row
        assert "metrics step=" in r.stdout, r.stdout[-2000:]

    @pytest.mark.slow
    def test_kv_dtype_serves_quantized_paged(self):
        # [slow: a second serving subprocess warming the paged server
        # ≈ 25s; the quantized datapath itself is tier-1-covered by
        # test_paged_serving.py::TestQuantizedKV]
        r = _run_example("examples/serving_demo.py",
                         ["--requests", "5", "--max-slots", "2",
                          "--kv-dtype", "int8"])
        assert r.returncode == 0, r.stderr[-2000:]
        assert r.stdout.count("req ") == 5, r.stdout[-2000:]
        assert "kv: dtype=int8 bits=8" in r.stdout, r.stdout[-2000:]
        assert "done: 5 requests" in r.stdout, r.stdout[-2000:]

    @pytest.mark.slow
    def test_tp_path_serves_sharded_replica(self):
        # [slow: a serving subprocess warming the tensor-parallel
        # paged server ≈ 30s; the sharded datapath itself is
        # tier-1-covered by test_tp_serving.py]
        r = _run_example("examples/serving_demo.py",
                         ["--requests", "4", "--max-slots", "2",
                          "--tp", "2"])
        assert r.returncode == 0, r.stderr[-2000:]
        assert r.stdout.count("req ") == 4, r.stdout[-2000:]
        assert "tp: chips_per_replica=2" in r.stdout, r.stdout[-2000:]
        assert "done: 4 requests" in r.stdout, r.stdout[-2000:]
        assert "chips_per_replica=2" in r.stdout, r.stdout[-2000:]

    @pytest.mark.slow
    def test_tp_composes_with_replicas_fleet(self):
        # [slow: a serving subprocess warming a 2×2 fleet (2 replicas
        # × 2 chips, each on its own device slice) ≈ 60s; the merged
        # chips gauges are tier-1-covered by test_tp_serving.py]
        r = _run_example("examples/serving_demo.py",
                         ["--requests", "4", "--max-slots", "2",
                          "--tp", "2", "--replicas", "2"])
        assert r.returncode == 0, r.stderr[-2000:]
        assert r.stdout.count("req ") == 4, r.stdout[-2000:]
        assert "fleet: replicas=2 ready=2 chips_per_replica=2 " \
               "chips_total=4" in r.stdout, r.stdout[-2000:]
        assert "done: 4 requests" in r.stdout, r.stdout[-2000:]

    @pytest.mark.slow
    def test_plan_auto_respects_pinned_axis(self):
        # [slow: a serving subprocess warming a 2-chip TP replica ≈
        # 30s like the --tp smoke]  review regression: an explicit
        # flag PINS its axis — with replicas pinned at 1 on a 2-chip
        # budget the planner must pick the scored 1x2 TP split (never
        # graft an unscored combination or override the pin)
        r = _run_example("examples/serving_demo.py",
                         ["--plan", "auto", "--chips", "2",
                          "--replicas", "1", "--requests", "4",
                          "--max-slots", "2"])
        assert r.returncode == 0, r.stderr[-2000:]
        assert "plan: auto -> 1x2 (replicas x tp)" in r.stdout, \
            r.stdout[-2000:]
        assert "tp: chips_per_replica=2" in r.stdout, r.stdout[-2000:]
        assert "done: 4 requests" in r.stdout, r.stdout[-2000:]

    @pytest.mark.slow
    def test_plan_auto_serves_planned_split(self):
        # [slow: a serving subprocess warming a 2-replica fleet ≈ 25s
        # like the --replicas smoke; the planner itself is
        # tier-1-covered by test_plan.py]  ISSUE-15 satellite: the
        # replicas×tp split comes from apex_tpu.plan(objective=
        # "serve") — on a 2-chip budget the per-chip score picks the
        # 2×1 fleet (the tp_serving protocol's throughput ceiling)
        r = _run_example("examples/serving_demo.py",
                         ["--plan", "auto", "--chips", "2",
                          "--requests", "4", "--max-slots", "2"])
        assert r.returncode == 0, r.stderr[-2000:]
        assert "plan: auto -> 2x1 (replicas x tp)" in r.stdout, \
            r.stdout[-2000:]
        assert r.stdout.count("req ") == 4, r.stdout[-2000:]
        assert "fleet: replicas=2 ready=2" in r.stdout, \
            r.stdout[-2000:]
        assert "done: 4 requests" in r.stdout, r.stdout[-2000:]

    @pytest.mark.slow
    def test_replicas_path_routes_through_fleet(self):
        # [slow: a second serving subprocess warming 2 paged replicas
        # ≈ 25s; the fleet router itself is tier-1-covered by
        # test_fleet.py and the single-server demo test above stays]
        r = _run_example("examples/serving_demo.py",
                         ["--requests", "5", "--max-slots", "2",
                          "--replicas", "2"])
        assert r.returncode == 0, r.stderr[-2000:]
        assert r.stdout.count("req ") == 5, r.stdout[-2000:]
        assert "fleet: replicas=2 ready=2" in r.stdout, \
            r.stdout[-2000:]
        assert "done: 5 requests" in r.stdout, r.stdout[-2000:]
        # per-replica emissions aggregate into the one fleet writer,
        # namespaced — the printed rows carry replica<N>/ keys
        assert "metrics step=" in r.stdout, r.stdout[-2000:]
        assert "replica0/" in r.stdout or "replica1/" in r.stdout, \
            r.stdout[-2000:]


@pytest.mark.slow
class TestLlamaGenerateExample:
    # [slow: two subprocess generate runs incl. a torch cross-check
    # ≈ 85s; greedy parity stays tier-1-covered by test_generate and
    # test_serving]
    def test_greedy_matches_torch(self):
        r = _run_example("examples/llama_generate.py", [])
        assert r.returncode == 0, r.stderr[-2000:]
        assert "token-identical to torch" in r.stdout

    def test_windowed_sampling(self):
        r = _run_example(
            "examples/llama_generate.py",
            ["--window", "8", "--temperature", "0.8",
             "--max-new-tokens", "6"])
        assert r.returncode == 0, r.stderr[-2000:]
        assert r.stdout.count("cont:") == 2
