"""Tensor-parallel paged serving (ISSUE 13): one replica spans the mesh.

Correctness contracts under test:

- the GQA group→shard mapping (``ops.paged_attention.tp_head_shards``)
  and its loud config-time divisibility gate — ``kv_heads % tp != 0``
  raises a ``ValueError`` at construction, never a shape error deep
  inside shard_map (TransformerConfig, PagedEngine and InferenceServer
  all reject it);
- the sharded ``paged_attention`` op is BITWISE identical to the
  unsharded reference — MHA and GQA, decode and multi-token chunks,
  unquantized and int8 pages (per-(kv_head, page) scales shard on the
  same leading axis);
- the TP engine's pool and weights are ACTUALLY placed across the mesh
  (and stay so after steps — the sharding fixed point behind the
  retrace budgets);
- greedy decode through a TP engine with prefix sharing + speculative
  decoding on is token-identical to ``generate()``, and with int8
  pages additionally token-identical to the single-chip quantized
  engine (quantized chains are deterministic per (tokens, knobs), not
  generate-bitwise — the PR-8 band contract);
- a mixed-traffic soak on the sharded engine stays at the EXACT 5×1
  executable budget with zero retraces — TP changes where tensors
  live, not how many programs exist;
- ``InferenceServer(tp=)`` plumbing: health()/metrics gain
  ``chips_per_replica`` / ``mesh_shape`` / per-chip throughput;
- autotune winners are keyed on the PER-SHARD kv_heads count: a TP
  engine adopts the winner swept at ``kv_heads / tp`` and never the
  full-head-count one (and vice versa).

The fleet-level merged chips view lives in ``test_fleet.py``; the
sharded-replica kill soak in ``test_chaos.py``.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu.core.mesh import TENSOR_AXIS
from apex_tpu.models import (
    GPTConfig,
    GPTModel,
    LlamaConfig,
    LlamaModel,
    generate,
)
from apex_tpu.ops.paged_attention import (
    paged_attention,
    quantize_kv_pages,
    tp_head_shards,
)
from apex_tpu.serving import (
    InferenceServer,
    PagedEngine,
    Request,
    Scheduler,
    tp_mesh,
)
from apex_tpu.utils import MetricsWriter


@pytest.fixture(scope="module")
def gpt():
    cfg = GPTConfig.tiny(position_embedding="learned",
                         scan_layers=True)
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))
    return model, {"params": params["params"]}


@pytest.fixture(scope="module")
def mesh2():
    return tp_mesh(2)


#: the full serving stack — sharing + drafting + quantized pages —
#: built ONCE per module at both layouts (every test that needs a
#: warmed engine reuses these; trace counts must end the module at
#: exactly 1 each)
FULL_KW = dict(max_slots=3, block_size=8, prefill_chunk=4,
               share_prefixes=True, spec_tokens=3, kv_dtype="int8")


@pytest.fixture(scope="module")
def full_engines(gpt, mesh2):
    model, params = gpt
    single = PagedEngine(model, params, **FULL_KW)
    tp = PagedEngine(model, params, mesh=mesh2, **FULL_KW)
    single.warmup()
    tp.warmup()
    return single, tp


def _drain(engine, cases, *, queue_capacity=32):
    """Run ``cases`` = [(prompt, n, kwargs)] through a scheduler to
    completion; returns uid-ordered token lists."""
    sched = Scheduler(engine, queue_capacity=queue_capacity)
    for prompt, n, kw in cases:
        sched.submit(Request(prompt=np.asarray(prompt, np.int32),
                             max_new_tokens=int(n), **kw))
    events = sched.drain()
    out = {}
    for ev in events:
        out.setdefault(ev.request.uid, []).append(ev.token)
    return [out[uid] for uid in sorted(out)]


# --------------------------------------------------------------------- #
# the GQA group→shard mapping
# --------------------------------------------------------------------- #
class TestHeadShardMapping:
    def test_mha_even_split(self):
        assert tp_head_shards(8, 8, 2) == [((0, 4), (0, 4)),
                                           ((4, 8), (4, 8))]

    def test_gqa_groups_stay_whole(self):
        # 8 q heads over 4 kv heads (rep=2), tp=2: each shard owns 2
        # whole GQA groups — 4 q heads aligned with its 2 kv heads
        assert tp_head_shards(8, 4, 2) == [((0, 4), (0, 2)),
                                           ((4, 8), (2, 4))]
        # tp == kv_heads: one group per shard (rep q heads each)
        assert tp_head_shards(8, 4, 4) == [
            ((0, 2), (0, 1)), ((2, 4), (1, 2)),
            ((4, 6), (2, 3)), ((6, 8), (3, 4))]

    def test_tp1_is_the_whole_model(self):
        assert tp_head_shards(16, 4, 1) == [((0, 16), (0, 4))]

    def test_indivisible_kv_heads_raise_loudly(self):
        with pytest.raises(ValueError, match="divisible by the "
                                             "tensor-parallel"):
            tp_head_shards(8, 4, 3)

    def test_bad_gqa_ratio_raises(self):
        with pytest.raises(ValueError, match="must divide num_heads"):
            tp_head_shards(6, 4, 2)


# --------------------------------------------------------------------- #
# op-level: sharded == unsharded, bitwise
# --------------------------------------------------------------------- #
class TestShardedPagedAttentionOp:
    def _pool(self, rng, *, h, hk, d=16, bs=8, mb=5, b=3, s=1,
              kv_dtype=None):
        nb = b * mb + 1
        q = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
        kp = jnp.asarray(rng.normal(size=(hk, nb, bs, d)), jnp.float32)
        vp = jnp.asarray(rng.normal(size=(hk, nb, bs, d)), jnp.float32)
        tables = jnp.asarray(
            rng.permutation(np.arange(1, nb))[:b * mb].reshape(b, mb),
            jnp.int32)
        lengths = jnp.asarray(
            rng.integers(0, mb * bs - s, size=(b,)), jnp.int32)
        scales = {}
        if kv_dtype is not None:
            kp, vp, ks, vs = quantize_kv_pages(kp, vp, kv_dtype)
            scales = dict(k_scales=ks, v_scales=vs)
        return q, kp, vp, tables, lengths, scales

    @pytest.mark.parametrize("h,hk", [(4, 4), (8, 4)],
                             ids=["mha", "gqa"])
    @pytest.mark.parametrize("s", [1, 4], ids=["decode", "chunk"])
    def test_sharded_matches_unsharded(self, mesh2, h, hk, s):
        rng = np.random.default_rng(7)
        q, kp, vp, tables, lengths, _ = self._pool(
            rng, h=h, hk=hk, s=s)
        ref = paged_attention(q, kp, vp, tables, lengths)
        tp = paged_attention(q, kp, vp, tables, lengths,
                             mesh=mesh2, shard_axis=TENSOR_AXIS)
        np.testing.assert_array_equal(np.asarray(ref), np.asarray(tp))

    def test_sharded_matches_unsharded_int8(self, mesh2):
        # quant scales carry the same leading kv_heads axis and shard
        # with their pages — the in-shard dequant is bitwise the
        # single-chip one
        rng = np.random.default_rng(8)
        q, kp, vp, tables, lengths, scales = self._pool(
            rng, h=8, hk=4, s=2, kv_dtype="int8")
        ref = paged_attention(q, kp, vp, tables, lengths, **scales)
        tp = paged_attention(q, kp, vp, tables, lengths, **scales,
                             mesh=mesh2, shard_axis=TENSOR_AXIS)
        np.testing.assert_array_equal(np.asarray(ref), np.asarray(tp))

    def test_sharded_under_jit(self, mesh2):
        rng = np.random.default_rng(9)
        q, kp, vp, tables, lengths, _ = self._pool(rng, h=4, hk=4)
        ref = paged_attention(q, kp, vp, tables, lengths)
        fn = jax.jit(lambda q: paged_attention(
            q, kp, vp, tables, lengths, mesh=mesh2,
            shard_axis=TENSOR_AXIS))
        np.testing.assert_array_equal(np.asarray(ref),
                                      np.asarray(fn(q)))

    def test_indivisible_heads_raise_inside_op(self, mesh2):
        rng = np.random.default_rng(10)
        q, kp, vp, tables, lengths, _ = self._pool(rng, h=3, hk=3)
        with pytest.raises(ValueError, match="divisible"):
            paged_attention(q, kp, vp, tables, lengths,
                            mesh=mesh2, shard_axis=TENSOR_AXIS)


# --------------------------------------------------------------------- #
# config-time validation — the loud gates
# --------------------------------------------------------------------- #
class TestConfigTimeValidation:
    def test_transformer_config_requires_paged(self, mesh2):
        with pytest.raises(ValueError, match="kv_cache='paged'"):
            GPTConfig.tiny(kv_shard_axis=TENSOR_AXIS, kv_mesh=mesh2)

    def test_axis_and_mesh_come_together(self):
        with pytest.raises(ValueError, match="come together"):
            GPTConfig.tiny(kv_cache="paged", kv_pool_blocks=4,
                           kv_shard_axis=TENSOR_AXIS)

    def test_axis_must_exist_in_mesh(self, mesh2):
        with pytest.raises(ValueError, match="not an[\\s]+axis"):
            GPTConfig.tiny(kv_cache="paged", kv_pool_blocks=4,
                           kv_shard_axis="nonesuch", kv_mesh=mesh2)

    def test_kv_heads_divisibility_at_config_time(self):
        # tiny GPT has 2 kv heads; a 3-wide tensor axis cannot split
        # them — the error fires in the frozen config's __post_init__
        mesh3 = tp_mesh(3)
        with pytest.raises(ValueError, match="divisible by the "
                                             "tensor-parallel"):
            GPTConfig.tiny(kv_cache="paged", kv_pool_blocks=4,
                           kv_shard_axis=TENSOR_AXIS, kv_mesh=mesh3)

    def test_engine_rejects_indivisible_tp(self, gpt):
        model, params = gpt
        with pytest.raises(ValueError, match="divisible by the "
                                             "tensor-parallel"):
            PagedEngine(model, params, mesh=3)

    def test_server_rejects_tp_on_dense(self, gpt):
        model, params = gpt
        with pytest.raises(ValueError, match="require "
                                             "kv_cache='paged'"):
            InferenceServer(model, params, tp=2)

    def test_server_rejects_tp_mesh_mismatch(self, gpt, mesh2):
        model, params = gpt
        with pytest.raises(ValueError, match="disagrees with mesh"):
            InferenceServer(model, params, kv_cache="paged",
                            tp=4, mesh=mesh2)
        # mesh may be the engine's int spelling: still the loud
        # mismatch error, never an AttributeError on .shape
        with pytest.raises(ValueError, match="disagrees with mesh"):
            InferenceServer(model, params, kv_cache="paged",
                            tp=4, mesh=2)

    def test_tp_mesh_needs_enough_devices(self):
        with pytest.raises(ValueError, match="devices"):
            tp_mesh(2, jax.devices()[:1])

    def test_engine_rejects_mesh_without_tensor_axis(self, gpt):
        # loud, not a silent single-chip fallback: a foreign-axis mesh
        # means the caller BELIEVES they are tensor-parallel
        model, params = gpt
        foreign = jax.sharding.Mesh(
            np.asarray(jax.devices()[:2]), ("model",))
        with pytest.raises(ValueError, match="no 'tensor' axis"):
            PagedEngine(model, params, mesh=foreign)


class TestTrafficModelICI:
    def test_ici_column_and_per_chip_reads(self):
        import bench_configs as bc

        tm1 = bc._serving_traffic_model(
            num_layers=2, kv_heads=2, head_dim=16, max_seq_len=64,
            live_tokens=24, slots=2, block_size=8, dtype_bytes=4)
        assert tm1["tp"] == 1 and tm1["ici_bytes_per_step"] == 0
        tm2 = bc._serving_traffic_model(
            num_layers=2, kv_heads=2, head_dim=16, max_seq_len=64,
            live_tokens=24, slots=2, block_size=8, dtype_bytes=4,
            tp=2, hidden_size=32)
        # ring all-reduce: 2 reduces/layer × (slots·hidden·bytes) ×
        # 2(tp-1)/tp per chip
        assert tm2["ici_bytes_per_step_per_chip"] == int(
            2 * 2 * 2 * 32 * 4 * 2 * (2 - 1) / 2)
        assert tm2["ici_bytes_per_step"] == \
            2 * tm2["ici_bytes_per_step_per_chip"]
        assert tm2["paged_kv_read_bytes_per_step_per_chip"] * 2 == \
            tm2["paged_kv_read_bytes_per_step"]
        # the kv-head-sharded read column is live-dependent, like its
        # single-chip parent
        with pytest.raises(ValueError, match="hidden_size"):
            bc._serving_traffic_model(
                num_layers=2, kv_heads=2, head_dim=16, max_seq_len=64,
                live_tokens=24, slots=2, block_size=8, tp=2)

    def test_quantized_per_chip_read_uses_quantized_bytes(self):
        import bench_configs as bc

        tm = bc._serving_traffic_model(
            num_layers=2, kv_heads=2, head_dim=16, max_seq_len=64,
            live_tokens=24, slots=2, block_size=8, dtype_bytes=4,
            kv_dtype="int8", tp=2, hidden_size=32)
        assert tm["paged_kv_read_bytes_per_step_per_chip_quantized"] \
            * 2 == tm["paged_kv_read_bytes_per_step_quantized"]
        # the quantized per-chip read must sit well under the
        # unquantized one (1-byte codes vs 4-byte floats)
        assert tm["paged_kv_read_bytes_per_step_per_chip_quantized"] \
            < tm["paged_kv_read_bytes_per_step_per_chip"]


# --------------------------------------------------------------------- #
# engine-level: placement, parity, budgets
# --------------------------------------------------------------------- #
def _find_leaf(tree, name):
    hits = [leaf for path, leaf in
            jax.tree_util.tree_flatten_with_path(tree)[0]
            if str(getattr(path[-1], "key", path[-1])) == name]
    assert hits, f"no {name} leaf"
    return hits[0]


class TestTPPlacement:
    def test_pool_and_weights_span_the_mesh(self, full_engines):
        """The memory story is real only if the arrays are really
        split: the pool leaves shard their kv_heads dim over both
        chips, at least one weight is sharded per its GSPMD
        annotation, and the block tables stay replicated."""
        _single, tp = full_engines
        pk = _find_leaf(tp.cache, "paged_key")
        spec = pk.sharding.spec
        assert TENSOR_AXIS in spec, spec
        assert spec.index(TENSOR_AXIS) == pk.ndim - 4
        ks = _find_leaf(tp.cache, "key_scales")
        assert ks.sharding.spec.index(TENSOR_AXIS) == ks.ndim - 2
        bt = _find_leaf(tp.cache, "block_tables")
        assert TENSOR_AXIS not in tuple(bt.sharding.spec)
        sharded_params = [
            leaf for leaf in jax.tree.leaves(tp._variables)
            if TENSOR_AXIS in tuple(getattr(
                getattr(leaf, "sharding", None), "spec", ()) or ())]
        assert sharded_params, "no weight actually sharded"

    def test_placement_is_a_fixed_point_across_steps(self,
                                                     full_engines):
        # after real traffic the donated cache must land exactly where
        # it started (the retrace budgets depend on it)
        _single, tp = full_engines
        tp.admit(0, np.arange(5, dtype=np.int32) + 1,
                 max_new_tokens=2)
        while tp._tenants[0] is not None:
            out = tp.step()
            if int(out.counts[0]) and bool(out.finished[0]):
                break
        tp.release(0)
        pk = _find_leaf(tp.cache, "paged_key")
        assert pk.sharding.spec.index(TENSOR_AXIS) == pk.ndim - 4

    def test_gauges(self, full_engines):
        single, tp = full_engines
        assert single.chips_per_replica == 1
        assert single.mesh_shape is None
        assert tp.chips_per_replica == 2
        assert tp.mesh_shape == {"tensor": 2}


class TestTPTokenIdentity:
    #: prompt lengths straddling every boundary that matters at
    #: block_size=8 / prefill_chunk=4: page-1, page, page+1, chunk
    #: multiples, and a shared-prefix continuation
    LENGTHS = (7, 8, 9, 12, 16)

    def test_full_stack_tp_vs_single_chip(self, full_engines):
        """Sharing + drafting + int8 pages: the sharded engine's
        greedy chains equal the single-chip quantized engine's, page
        pools drain to 0 on both, and sharing actually engaged (the
        first 8-token block is common to every prompt)."""
        single, tp = full_engines
        rng = np.random.default_rng(3)
        base = rng.integers(0, 1024, size=(8,)).astype(np.int32)
        cases = []
        for i, L in enumerate(self.LENGTHS):
            tail = rng.integers(0, 1024, size=(max(L - 8, 0),))
            prompt = np.concatenate([base, tail])[:L].astype(np.int32)
            cases.append((prompt, 9, dict(seed=i)))
        # one sampled tenant rides along (sampled chains are a
        # function of the request's own seed — layout-independent)
        cases.append((base, 6, dict(temperature=0.9, top_p=0.9,
                                    seed=42)))
        got_single = _drain(single, cases)
        got_tp = _drain(tp, cases)
        assert got_single == got_tp
        assert single.blocks_in_use == 0 and tp.blocks_in_use == 0
        assert tp.trie_blocks == 0        # trie forgot freed pages

    def test_tp_greedy_token_identical_to_generate(self, gpt, mesh2):
        """Unquantized TP engine with sharing + drafting on: greedy
        output token-identical to ``generate()`` (the acceptance
        anchor — int8 runs compare engine-to-engine above because
        quantization is a band vs generate, by design)."""
        model, params = gpt
        eng = PagedEngine(model, params, max_slots=3, block_size=8,
                          prefill_chunk=4, share_prefixes=True,
                          spec_tokens=3, mesh=mesh2)
        eng.warmup()
        rng = np.random.default_rng(5)
        cases = [(rng.integers(0, 1024, size=(L,)).astype(np.int32),
                  8, dict(seed=i))
                 for i, L in enumerate(self.LENGTHS)]
        got = _drain(eng, cases)
        for (prompt, n, _kw), toks in zip(cases, got):
            ref = np.asarray(generate(
                model, params, jnp.asarray(prompt[None]),
                max_new_tokens=n))[0, len(prompt):]
            np.testing.assert_array_equal(
                np.asarray(toks), ref,
                err_msg=f"TP engine diverged from generate() at "
                        f"L={len(prompt)}")
        assert eng.blocks_in_use == 0
        # the soak engine budget: 5 executables × 1 trace
        assert eng.trace_counts == {
            "decode_step": 1, "prefill_step": 1, "admit": 1,
            "release": 1, "spec_step": 1}


class TestTPZeroRetraceSoak:
    def test_mixed_traffic_at_exactly_5x1(self, full_engines):
        """Steady-state mixed traffic (greedy / temperature / top-p /
        eos budgets, shared and private prompts, drafted and
        draft-hostile) on the SHARDED engine: the documented budget is
        5 executables × 1 trace — any retrace raises RetraceError, and
        the counts must still read exactly 1 afterwards."""
        _single, tp = full_engines
        before = dict(tp.trace_counts)
        assert all(v == 1 for v in before.values()), before
        rng = np.random.default_rng(11)
        cases = []
        for i in range(8):
            L = int(rng.integers(2, 20))
            kw = {"seed": i}
            if i % 3 == 1:
                kw.update(temperature=1.1, top_k=7)
            if i % 3 == 2:
                kw.update(temperature=0.8, top_p=0.85)
            cases.append((rng.integers(0, 1024, size=(L,)), 6, kw))
        _drain(tp, cases)
        after = dict(tp.trace_counts)
        assert after == {"decode_step": 1, "prefill_step": 1,
                         "admit": 1, "release": 1, "spec_step": 1}
        assert tp.blocks_in_use == 0


# --------------------------------------------------------------------- #
# server plumbing
# --------------------------------------------------------------------- #
class TestTPServer:
    def test_tp_server_serves_and_reports_mesh(self, gpt):
        model, params = gpt
        rows = []
        writer = MetricsWriter(sink=lambda s, m: rows.append(m))
        server = InferenceServer(
            model, params, max_slots=2, kv_cache="paged",
            block_size=8, prefill_chunk=4, tp=2,
            metrics=writer, metrics_interval=1)
        rng = np.random.default_rng(2)
        with server:
            prompts = [rng.integers(0, 1024, size=(L,)).astype(
                np.int32) for L in (5, 11)]
            handles = [server.submit(p, max_new_tokens=6, seed=i)
                       for i, p in enumerate(prompts)]
            results = [h.result(timeout=300) for h in handles]
            health = server.health()
        assert health["chips_per_replica"] == 2
        assert health["mesh_shape"] == {"tensor": 2}
        # greedy through the TP server == generate()
        for p, toks in zip(prompts, results):
            ref = np.asarray(generate(
                model, params, jnp.asarray(p[None]),
                max_new_tokens=6))[0, len(p):]
            np.testing.assert_array_equal(np.asarray(toks), ref)
        merged = {}
        for m in rows:
            merged.update(m)
        assert merged.get("chips_per_replica") == 2
        assert "tokens_per_sec_per_chip" in merged
        assert merged["tokens_per_sec_per_chip"] * 2 == pytest.approx(
            merged["tokens_per_sec"])

    def test_single_chip_server_reports_one_chip(self, gpt):
        model, params = gpt
        server = InferenceServer(model, params, max_slots=1,
                                 kv_cache="paged", block_size=8,
                                 prefill_chunk=4)
        health = server.health()      # probe works unstarted
        assert health["chips_per_replica"] == 1
        assert "mesh_shape" not in health


# --------------------------------------------------------------------- #
# autotune: per-shard kv_heads keying
# --------------------------------------------------------------------- #
class TestAutotunePerShardKeys:
    def test_tp_engine_adopts_per_shard_winner_only(
            self, gpt, mesh2, tmp_path, monkeypatch):
        model, params = gpt
        monkeypatch.setenv("APEX_TPU_AUTOTUNE_CACHE",
                           str(tmp_path / "at.json"))
        from apex_tpu.ops import autotune

        autotune.clear_cache()
        try:
            dt = str(jnp.dtype(model.cfg.dtype))
            hd = int(model.cfg.head_dim)
            # full-count winner (kv_heads=2) and per-shard winner
            # (kv_heads=1, what each of 2 chips actually serves)
            autotune._store(autotune._key("paged_attention", hd, dt,
                                          kv_heads=2), 32)
            autotune._store(autotune._key("paged_attention", hd, dt,
                                          kv_heads=1), 8)
            e1 = PagedEngine(model, params, max_slots=1, block_size=0)
            e2 = PagedEngine(model, params, max_slots=1, block_size=0,
                             mesh=mesh2)
            assert e1.block_size == 32
            assert e2.block_size == 8
        finally:
            autotune.clear_cache()

    def test_missing_per_shard_entry_never_falls_back(
            self, gpt, mesh2, tmp_path, monkeypatch):
        """Only a full-head-count winner cached: the TP engine must
        NOT adopt it — it takes the built-in default instead (the
        satellite's exact failure mode)."""
        model, params = gpt
        monkeypatch.setenv("APEX_TPU_AUTOTUNE_CACHE",
                           str(tmp_path / "at.json"))
        from apex_tpu.ops import autotune

        autotune.clear_cache()
        try:
            dt = str(jnp.dtype(model.cfg.dtype))
            hd = int(model.cfg.head_dim)
            autotune._store(autotune._key("paged_attention", hd, dt,
                                          kv_heads=2), 32)
            tp_engine = PagedEngine(model, params, max_slots=1,
                                    block_size=0, mesh=mesh2)
            assert tp_engine.block_size == 16      # default, not 32
        finally:
            autotune.clear_cache()

    def test_auto_pair_keyed_per_shard(self, gpt, mesh2, tmp_path,
                                       monkeypatch):
        model, params = gpt
        monkeypatch.setenv("APEX_TPU_AUTOTUNE_CACHE",
                           str(tmp_path / "at.json"))
        from apex_tpu.ops import autotune

        autotune.clear_cache()
        try:
            dt = str(jnp.dtype(model.cfg.dtype))
            hd = int(model.cfg.head_dim)
            autotune._store(autotune._key("paged_attention_pair", hd,
                                          dt, kv_heads=1),
                            [8, "int8"])
            tp_engine = PagedEngine(model, params, max_slots=1,
                                    block_size=0, kv_dtype="auto",
                                    mesh=mesh2)
            assert tp_engine.kv_dtype == "int8"
            assert tp_engine.block_size == 8
            # the single-chip engine queries kv_heads=2: a miss
            single = PagedEngine(model, params, max_slots=1,
                                 block_size=0, kv_dtype="auto")
            assert single.kv_dtype is None
        finally:
            autotune.clear_cache()


# --------------------------------------------------------------------- #
# slow tier: the GQA model twin
# --------------------------------------------------------------------- #
@pytest.mark.slow
class TestLlamaGQATwinSlow:
    def test_gqa_tp_engine_matches_single_chip(self, mesh2):
        """Llama tiny (4 q heads over 2 kv heads): the engine-level
        GQA twin of the tier-1 GPT parity — each chip owns one whole
        GQA group.  [slow: two extra engine builds on a second model;
        the mapping itself is tier-1-covered op-level.]"""
        cfg = LlamaConfig.tiny(scan_layers=True)
        model = LlamaModel(cfg)
        params = {"params": model.init(
            jax.random.PRNGKey(0),
            jnp.zeros((1, 4), jnp.int32))["params"]}
        kw = dict(max_slots=2, block_size=8, prefill_chunk=4,
                  share_prefixes=True, spec_tokens=2)
        single = PagedEngine(model, params, **kw)
        tp = PagedEngine(model, params, mesh=mesh2, **kw)
        rng = np.random.default_rng(6)
        cases = [(rng.integers(0, cfg.vocab_size,
                               size=(L,)).astype(np.int32),
                  7, dict(seed=i))
                 for i, L in enumerate((7, 8, 13))]
        assert _drain(single, cases) == _drain(tp, cases)
        assert tp.blocks_in_use == 0


# --------------------------------------------------------------------- #
# fused decode prologue under TP (ISSUE 14): shard-local write
# --------------------------------------------------------------------- #
class TestShardedFusedDecodePrologue:
    """``paged_decode_fused`` over the mesh: the new K/V rows shard on
    kv_heads beside the pool, the write stays shard-local, and the
    sharded step is BITWISE the single-chip one — output, written
    pages, codes and scales (the PR-12 layout is preserved through the
    fusion)."""

    def _setup(self, rng, *, h, hk, kv_dtype=None, d=16, bs=8, mb=5,
               b=3, S=None):
        from apex_tpu.ops.rope import rope_cos_sin

        S = S or mb * bs
        nb = b * mb + 1
        kp = jnp.asarray(rng.normal(size=(hk, nb, bs, d)), jnp.float32)
        vp = jnp.asarray(rng.normal(size=(hk, nb, bs, d)), jnp.float32)
        scales = {}
        if kv_dtype is not None:
            kp, vp, ks, vs = quantize_kv_pages(kp, vp, kv_dtype)
            scales = dict(k_scales=ks, v_scales=vs,
                          chunk_lens=jnp.ones((b,), jnp.int32))
        tables = jnp.asarray(
            rng.permutation(np.arange(1, nb))[:b * mb].reshape(b, mb),
            jnp.int32)
        lengths = jnp.asarray(
            rng.integers(0, mb * bs - 1, size=(b,)), jnp.int32)
        q = jnp.asarray(rng.normal(size=(b, 1, h, d)), jnp.float32)
        nk = jnp.asarray(rng.normal(size=(b, 1, hk, d)), jnp.float32)
        nv = jnp.asarray(rng.normal(size=(b, 1, hk, d)), jnp.float32)
        cos, sin = rope_cos_sin(S, d)
        pc = np.minimum(np.asarray(lengths)[:, None], S - 1)
        rope = dict(cos_b=jnp.asarray(cos[pc][:, :, None, :]),
                    sin_b=jnp.asarray(sin[pc][:, :, None, :]))
        return q, nk, nv, kp, vp, tables, lengths, rope, scales, S

    @pytest.mark.parametrize("h,hk", [(4, 4), (8, 4)],
                             ids=["mha", "gqa"])
    def test_sharded_matches_unsharded(self, mesh2, h, hk):
        from apex_tpu.ops.paged_attention import paged_decode_fused

        rng = np.random.default_rng(21)
        (q, nk, nv, kp, vp, tables, lengths, rope, sc,
         S) = self._setup(rng, h=h, hk=hk)
        ref = jax.jit(lambda *a: paged_decode_fused(
            *a, max_seq_len=S, **rope))(q, nk, nv, kp, vp, tables,
                                        lengths)
        tp = jax.jit(lambda *a: paged_decode_fused(
            *a, max_seq_len=S, **rope, mesh=mesh2,
            shard_axis=TENSOR_AXIS))(q, nk, nv, kp, vp, tables,
                                     lengths)
        for a, b_ in zip(ref, tp):
            np.testing.assert_array_equal(np.asarray(a),
                                          np.asarray(b_))

    def test_sharded_matches_unsharded_int8(self, mesh2):
        from apex_tpu.ops.paged_attention import paged_decode_fused

        rng = np.random.default_rng(22)
        (q, nk, nv, kp, vp, tables, lengths, rope, sc,
         S) = self._setup(rng, h=8, hk=4, kv_dtype="int8")
        ref = jax.jit(lambda *a: paged_decode_fused(
            *a, max_seq_len=S, **rope, **sc))(q, nk, nv, kp, vp,
                                              tables, lengths)
        tp = jax.jit(lambda *a: paged_decode_fused(
            *a, max_seq_len=S, **rope, **sc, mesh=mesh2,
            shard_axis=TENSOR_AXIS))(q, nk, nv, kp, vp, tables,
                                     lengths)
        for a, b_ in zip(ref, tp):
            np.testing.assert_array_equal(np.asarray(a),
                                          np.asarray(b_))
