"""Fused BatchNorm(+add+ReLU) kernels and the ResNet traffic levers
(ISSUE 3 tentpole).

Contracts under test:
- fwd AND bwd of ``batch_norm_train`` match flax ``nn.BatchNorm`` /
  the jnp golden composition on BOTH dispatch paths (xla +
  pallas_interpret), train and eval mode, with/without residual-add
  and ReLU, odd channel counts (XLA-fallback envelope), bf16;
- the space-to-depth stem computes exactly the 7×7/stride-2 conv
  (weight-transform parity, model logits parity, torchvision-importer
  compatibility);
- the compiled resnet50 train step's cost-model bytes drop with
  ``fused_bn=True`` + s2d stem (the without-a-chip half of the ISSUE-3
  acceptance; the on-chip A/B rows in BENCH_CONFIGS.json are the real
  certification).
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import flax.linen as nn

from apex_tpu.ops.batch_norm import (
    batch_norm_inference,
    batch_norm_reference,
    batch_norm_train,
)

IMPLS = ("xla", "pallas_interpret")


def _data(rng, shape=(4, 6, 6, 64), dtype=jnp.float32):
    x = jnp.asarray(rng.normal(size=shape), dtype)
    res = jnp.asarray(rng.normal(size=shape), dtype)
    c = shape[-1]
    w = jnp.asarray(rng.normal(size=(c,)) + 1.0, jnp.float32)
    b = jnp.asarray(rng.normal(size=(c,)), jnp.float32)
    return x, res, w, b


class TestFusedBatchNormGolden:
    @pytest.mark.parametrize("impl", IMPLS)
    @pytest.mark.parametrize("act", [None, "relu"])
    @pytest.mark.parametrize("use_res", [False, True])
    def test_forward_matches_reference(self, rng, impl, act, use_res):
        x, res, w, b = _data(rng)
        r = res if use_res else None
        yr, mr, vr = batch_norm_reference(x, w, b, residual=r, act=act)
        y, m, v = batch_norm_train(x, w, b, residual=r, act=act,
                                   implementation=impl)
        np.testing.assert_allclose(np.asarray(y), np.asarray(yr),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(m), np.asarray(mr),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(v), np.asarray(vr),
                                   rtol=1e-5, atol=1e-5)

    @pytest.mark.l0
    @pytest.mark.parametrize("impl", IMPLS)
    @pytest.mark.parametrize("act", [None, "relu"])
    @pytest.mark.parametrize("use_res", [False, True])
    def test_backward_matches_autodiff_of_reference(self, rng, impl,
                                                    act, use_res):
        """The custom_vjp (single-reduction bwd, mask recompute, psum
        hooks) must equal jax.grad through the plain composition —
        including the mean/var output cotangents."""
        x, res, w, b = _data(rng)
        r = res if use_res else None
        argnums = (0, 1, 2, 3) if use_res else (0, 1, 2)

        def loss_ref(x, w, b, r):
            y, m, v = batch_norm_reference(x, w, b, residual=r, act=act)
            return (jnp.sum(y * jnp.cos(y)) + jnp.sum(m * 2.0)
                    + jnp.sum(v * 3.0))

        def loss_fused(x, w, b, r):
            y, m, v = batch_norm_train(x, w, b, residual=r, act=act,
                                       implementation=impl)
            return (jnp.sum(y * jnp.cos(y)) + jnp.sum(m * 2.0)
                    + jnp.sum(v * 3.0))

        gr = jax.grad(loss_ref, argnums)(x, w, b, r)
        gf = jax.grad(loss_fused, argnums)(x, w, b, r)
        for a, bb in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(bb),
                                       rtol=2e-4, atol=2e-4)

    def test_matches_flax_batchnorm(self, rng):
        x, _, w, b = _data(rng)
        bn = nn.BatchNorm(use_running_average=False, momentum=0.9,
                          epsilon=1e-5)
        variables = bn.init(jax.random.PRNGKey(0), x)
        variables = {"params": {"scale": w, "bias": b},
                     "batch_stats": variables["batch_stats"]}
        want, _ = bn.apply(variables, x, mutable=["batch_stats"])
        for impl in IMPLS:
            y, _, _ = batch_norm_train(x, w, b, implementation=impl)
            np.testing.assert_allclose(np.asarray(y), np.asarray(want),
                                       rtol=1e-5, atol=1e-5)

    def test_odd_channels_fall_back_and_match(self, rng):
        # C=5: outside the kernel envelope — auto must dispatch to the
        # XLA path and still match the reference; forcing pallas raises
        x, res, w, b = _data(rng, shape=(4, 3, 3, 5))
        y, m, v = batch_norm_train(x, w, b, residual=res, act="relu")
        yr, mr, vr = batch_norm_reference(x, w, b, residual=res,
                                          act="relu")
        np.testing.assert_allclose(np.asarray(y), np.asarray(yr),
                                   rtol=1e-5, atol=1e-5)
        with pytest.raises(ValueError, match="envelope"):
            batch_norm_train(x, w, b, implementation="pallas")

    def test_bf16_within_tolerance(self, rng):
        x, res, w, b = _data(rng, dtype=jnp.bfloat16)
        yr, _, _ = batch_norm_reference(x, w, b, residual=res,
                                        act="relu")
        for impl in IMPLS:
            y, _, _ = batch_norm_train(x, w, b, residual=res,
                                       act="relu", implementation=impl)
            np.testing.assert_allclose(
                np.asarray(y, np.float32), np.asarray(yr, np.float32),
                rtol=2e-2, atol=2e-2)

    def test_inference_matches_syncbn_eval_math(self, rng):
        x, _, w, b = _data(rng)
        mean = jnp.asarray(rng.normal(size=(64,)), jnp.float32)
        var = jnp.asarray(rng.random(size=(64,)) + 0.5, jnp.float32)
        got = batch_norm_inference(x, mean, var, w, b, eps=1e-5)
        want = ((x.astype(jnp.float32) - mean)
                * jax.lax.rsqrt(var + 1e-5) * w + b).astype(x.dtype)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_validation(self, rng):
        x, res, w, b = _data(rng)
        with pytest.raises(ValueError, match="act"):
            batch_norm_train(x, w, b, act="gelu")
        with pytest.raises(ValueError, match="residual shape"):
            batch_norm_train(x, w, b, residual=res[:2])


class TestSyncBatchNormFusedLocal:
    """fused=True through the module (single device — the cross-device
    agreement lives in tests/test_parallel.py)."""

    def test_module_fused_matches_unfused(self, rng):
        from apex_tpu.parallel import SyncBatchNorm

        x = jnp.asarray(rng.normal(size=(8, 4, 4, 16)), jnp.float32)
        res = jnp.asarray(rng.normal(size=x.shape), jnp.float32)
        for act, use_res in ((None, False), ("relu", False),
                             ("relu", True)):
            kw = dict(use_running_average=False, axis_names=None,
                      act=act)
            a = SyncBatchNorm(fused=False, **kw)
            variables = a.init(jax.random.PRNGKey(0), x)
            r = res if use_res else None
            ya, mut_a = a.apply(variables, x, residual=r,
                                mutable=["batch_stats"])
            yb, mut_b = SyncBatchNorm(fused=True, **kw).apply(
                variables, x, residual=r, mutable=["batch_stats"])
            np.testing.assert_allclose(np.asarray(ya), np.asarray(yb),
                                       rtol=1e-5, atol=1e-5)
            for la, lb in zip(jax.tree.leaves(mut_a),
                              jax.tree.leaves(mut_b)):
                np.testing.assert_allclose(
                    np.asarray(la), np.asarray(lb), rtol=1e-5,
                    atol=1e-5)

    def test_eval_mode_ignores_fused_flag(self, rng):
        from apex_tpu.parallel import SyncBatchNorm

        x = jnp.asarray(rng.normal(size=(4, 8)), jnp.float32)
        variables = SyncBatchNorm(use_running_average=False).init(
            jax.random.PRNGKey(0), x)
        ya = SyncBatchNorm(use_running_average=True,
                           fused=False).apply(variables, x)
        yb = SyncBatchNorm(use_running_average=True,
                           fused=True).apply(variables, x)
        np.testing.assert_array_equal(np.asarray(ya), np.asarray(yb))


def _tiny_resnet(**kw):
    from apex_tpu.models.resnet import ResNet, ResNetConfig

    kw.setdefault("stage_sizes", (1, 1))
    return ResNet(ResNetConfig(num_classes=5, width=8, **kw))


class TestResNetFusedBN:
    def test_fused_matches_unfused(self, rng):
        """Logits and batch_stats agree between the fused and plain BN
        paths of the full model (all three _BN wirings: act-only,
        residual+act, bare).  Gradient agreement lives in the slow
        tier (the model-level grad compile costs ~30 s on CPU; the
        per-op backward is golden-tested above)."""
        x = jnp.asarray(rng.normal(size=(2, 32, 32, 3)), jnp.float32)
        m = _tiny_resnet()
        mf = _tiny_resnet(fused_bn=True)
        v = m.init(jax.random.PRNGKey(0), x, train=True)
        out, mut = m.apply(v, x, train=True, mutable=["batch_stats"])
        outf, mutf = mf.apply(v, x, train=True,
                              mutable=["batch_stats"])
        np.testing.assert_allclose(np.asarray(outf), np.asarray(out),
                                   rtol=1e-4, atol=1e-4)
        for a, b in zip(jax.tree.leaves(mut), jax.tree.leaves(mutf)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-5)

    @pytest.mark.slow
    def test_fused_grads_match_unfused(self, rng):
        # [slow: two whole-model grad compiles ≈ 30 s on CPU]
        x = jnp.asarray(rng.normal(size=(2, 32, 32, 3)), jnp.float32)
        m = _tiny_resnet()
        mf = _tiny_resnet(fused_bn=True)
        v = m.init(jax.random.PRNGKey(0), x, train=True)

        def loss(model, p):
            out, _ = model.apply(
                {"params": p, "batch_stats": v["batch_stats"]}, x,
                train=True, mutable=["batch_stats"])
            return jnp.sum(out ** 2)

        g1 = jax.grad(lambda p: loss(m, p))(v["params"])
        g2 = jax.grad(lambda p: loss(mf, p))(v["params"])
        for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-4, atol=5e-4)

    def test_eval_mode_parity(self, rng):
        x = jnp.asarray(rng.normal(size=(2, 32, 32, 3)), jnp.float32)
        m = _tiny_resnet()
        v = m.init(jax.random.PRNGKey(0), x, train=True)
        a = m.apply(v, x, train=False)
        b = _tiny_resnet(fused_bn=True).apply(v, x, train=False)
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=1e-5, atol=1e-5)


class TestSpaceToDepthStem:
    def test_conv_transform_exact(self, rng):
        """4×4/s1 conv over s2d input with the transformed kernel ==
        7×7/s2 conv (padding 3) over the raw input."""
        from apex_tpu.models.resnet import (
            space_to_depth,
            stem_conv_to_s2d,
        )

        x = jnp.asarray(rng.normal(size=(2, 64, 64, 3)), jnp.float32)
        w7 = jnp.asarray(rng.normal(size=(7, 7, 3, 16)), jnp.float32)
        want = jax.lax.conv_general_dilated(
            x, w7, window_strides=(2, 2), padding=[(3, 3), (3, 3)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        got = jax.lax.conv_general_dilated(
            space_to_depth(x), stem_conv_to_s2d(w7),
            window_strides=(1, 1), padding=[(2, 1), (2, 1)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-4)

    def test_model_logits_parity(self, rng):
        from apex_tpu.models.resnet import convert_stem_to_s2d

        x = jnp.asarray(rng.normal(size=(2, 32, 32, 3)), jnp.float32)
        m = _tiny_resnet()
        ms = _tiny_resnet(stem="s2d")
        v = m.init(jax.random.PRNGKey(0), x, train=True)
        want, _ = m.apply(v, x, train=True, mutable=["batch_stats"])
        got, _ = ms.apply(convert_stem_to_s2d(v), x, train=True,
                          mutable=["batch_stats"])
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-4)

    def test_space_to_depth_validation(self):
        from apex_tpu.models.resnet import space_to_depth

        with pytest.raises(ValueError, match="divisible"):
            space_to_depth(jnp.zeros((1, 7, 8, 3)))

    def test_bad_stem_config_raises(self, rng):
        x = jnp.asarray(rng.normal(size=(1, 8, 8, 3)), jnp.float32)
        m = _tiny_resnet(stem="wat")
        with pytest.raises(ValueError, match="stem"):
            m.init(jax.random.PRNGKey(0), x, train=True)


class TestTorchResnetImport:
    def _state_dict(self, rng, stage_sizes=(1, 2), width=8,
                    num_classes=5):
        sd = {}

        def bn(prefix, c):
            sd[prefix + ".weight"] = \
                rng.normal(size=(c,)).astype(np.float32) + 1.0
            sd[prefix + ".bias"] = \
                rng.normal(size=(c,)).astype(np.float32)
            sd[prefix + ".running_mean"] = \
                rng.normal(size=(c,)).astype(np.float32)
            sd[prefix + ".running_var"] = \
                rng.random(size=(c,)).astype(np.float32) + 0.5

        sd["conv1.weight"] = \
            rng.normal(size=(width, 3, 7, 7)).astype(np.float32) * 0.1
        bn("bn1", width)
        cin = width
        for i, nb in enumerate(stage_sizes):
            f = width * (2 ** i)
            for j in range(nb):
                stride = 2 if (j == 0 and i > 0) else 1
                p = f"layer{i + 1}.{j}"
                sd[p + ".conv1.weight"] = rng.normal(
                    size=(f, cin, 1, 1)).astype(np.float32) * 0.1
                bn(p + ".bn1", f)
                sd[p + ".conv2.weight"] = rng.normal(
                    size=(f, f, 3, 3)).astype(np.float32) * 0.1
                bn(p + ".bn2", f)
                sd[p + ".conv3.weight"] = rng.normal(
                    size=(4 * f, f, 1, 1)).astype(np.float32) * 0.1
                bn(p + ".bn3", 4 * f)
                if stride != 1 or cin != 4 * f:
                    sd[p + ".downsample.0.weight"] = rng.normal(
                        size=(4 * f, cin, 1, 1)).astype(np.float32) \
                        * 0.1
                    bn(p + ".downsample.1", 4 * f)
                cin = 4 * f
        sd["fc.weight"] = rng.normal(
            size=(num_classes, cin)).astype(np.float32) * 0.1
        sd["fc.bias"] = rng.normal(
            size=(num_classes,)).astype(np.float32)
        return sd

    def test_import_conv_and_s2d_agree(self, rng):
        """The same torchvision-layout checkpoint loaded into the
        plain and the s2d stem yields identical logits — the
        weight-transform path of the importer."""
        from apex_tpu.models.torch_import import load_torch_resnet

        sd = self._state_dict(rng)
        x = jnp.asarray(rng.normal(size=(2, 32, 32, 3)), jnp.float32)
        m = _tiny_resnet(stage_sizes=(1, 2))
        v = load_torch_resnet(
            m.init(jax.random.PRNGKey(0), x, train=True), sd)
        want = m.apply(v, x, train=False)
        # imported running stats are in play (eval mode): assert a
        # checkpoint BN leaf actually landed
        got_var = np.asarray(
            v["batch_stats"]["bn_stem"]["SyncBatchNorm_0"]["var"])
        np.testing.assert_allclose(got_var, sd["bn1.running_var"])

        ms = _tiny_resnet(stage_sizes=(1, 2), stem="s2d")
        vs = load_torch_resnet(
            ms.init(jax.random.PRNGKey(0), x, train=True), sd,
            stem="s2d")
        got = ms.apply(vs, x, train=False)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-4)

    def test_layer_count_mismatch_raises(self, rng):
        from apex_tpu.models.torch_import import load_torch_resnet

        sd = self._state_dict(rng, stage_sizes=(1, 1))
        x = jnp.zeros((1, 32, 32, 3), jnp.float32)
        m = _tiny_resnet(stage_sizes=(1, 2))
        with pytest.raises((ValueError, KeyError)):
            load_torch_resnet(
                m.init(jax.random.PRNGKey(0), x, train=True), sd)


class TestResnetTrafficModel:
    def test_fused_kernel_bound_ordering(self):
        import bench_configs

        tm = bench_configs._resnet_traffic_model(128, 224,
                                                 fused_bn=True)
        assert tm["floor"] < tm["bn_real"] < tm["bn_fused_kernel"]
        # the unfused call keeps the old two-key contract
        tm0 = bench_configs._resnet_traffic_model(128, 224)
        assert set(tm0) == {"floor", "bn_real"}
        assert tm0["bn_real"] == tm["bn_real"]


@pytest.mark.slow
class TestResnet50BytesAccessed:
    """ISSUE-3 acceptance, the without-a-chip half: compile (never
    execute) the resnet50 train step at a training-shaped batch and
    compare XLA's cost-model bytes.

    Two assertions, because the cost model overcounts conv-internal
    traffic (patch materializations — the repo's round-4/5 finding
    that demoted cost-model rooflines to diagnostics), which dilutes
    any BN-side win in the full-step total:

    - the full-step counted bytes must drop ≥ 10% with fused_bn + s2d
      (measured ≈ 13.7% at b=64/224/bf16);
    - of the BN-attributable counted bytes (full step minus a
      BN-free conv skeleton of the same architecture), the fused path
      must eliminate ≥ 20% (measured ≈ 35%) — the ISSUE-3 "≥20%"
      criterion scored on the denominator the levers can actually
      touch.  The on-chip A/B rows (BENCH_CONFIGS.json) certify the
      real-traffic frac.
    """

    B, SIZE = 64, 224

    def _step_bytes(self, model, with_stats):
        x = jnp.zeros((self.B, self.SIZE, self.SIZE, 3), jnp.bfloat16)
        y = jnp.zeros((self.B,), jnp.int32)
        if with_stats:
            v = jax.eval_shape(
                lambda: model.init(jax.random.PRNGKey(0), x,
                                   train=True))

            def step(params, bs, x, y):
                def loss_fn(p):
                    logits, mut = model.apply(
                        {"params": p, "batch_stats": bs}, x,
                        train=True, mutable=["batch_stats"])
                    oh = jax.nn.one_hot(y, 1000)
                    loss = -jnp.mean(jnp.sum(jax.nn.log_softmax(
                        logits.astype(jnp.float32)) * oh, axis=-1))
                    return loss, mut["batch_stats"]

                return jax.value_and_grad(loss_fn, has_aux=True)(
                    params)

            compiled = jax.jit(step).lower(
                v["params"], v["batch_stats"], x, y).compile()
        else:
            v = jax.eval_shape(
                lambda: model.init(jax.random.PRNGKey(0), x))

            def step(params, x, y):
                def loss_fn(p):
                    logits = model.apply({"params": p}, x)
                    oh = jax.nn.one_hot(y, 1000)
                    return -jnp.mean(jnp.sum(jax.nn.log_softmax(
                        logits.astype(jnp.float32)) * oh, axis=-1))

                return jax.value_and_grad(loss_fn)(params)

            compiled = jax.jit(step).lower(v["params"], x, y).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, list):
            ca = ca[0]
        return float(ca["bytes accessed"])

    def test_bytes_accessed_drop(self):
        from apex_tpu.models.resnet import ResNet, ResNetConfig

        cfg = ResNetConfig(stage_sizes=(3, 4, 6, 3), num_classes=1000,
                           dtype=jnp.bfloat16)
        base = self._step_bytes(ResNet(cfg), True)
        fused = self._step_bytes(
            ResNet(dataclasses.replace(cfg, fused_bn=True,
                                       stem="s2d")), True)
        skeleton = self._step_bytes(_ConvSkeleton(), False)
        full_drop = 1.0 - fused / base
        bn_attrib = base - skeleton
        eliminated = (base - fused) / bn_attrib
        assert fused < base, (base, fused)
        assert full_drop >= 0.10, (
            f"full-step cost-model bytes drop {full_drop:.3f} < 10% "
            f"(base {base:.3e}, fused {fused:.3e})")
        assert bn_attrib > 0, (base, skeleton)
        assert eliminated >= 0.20, (
            f"fused path eliminates only {eliminated:.3f} of the "
            f"BN-attributable counted bytes (base {base:.3e}, fused "
            f"{fused:.3e}, conv skeleton {skeleton:.3e})")


class _SkelBlock(nn.Module):
    """Bottleneck block with BN stripped (conv skeleton — the
    denominator of the BN-attributable bytes measurement)."""

    features: int
    stride: int = 1

    @nn.compact
    def __call__(self, x):
        conv = lambda f, k, s, name: nn.Conv(
            f, (k, k), (s, s), padding="SAME" if k > 1 else "VALID",
            use_bias=False, dtype=jnp.bfloat16, name=name)
        r = nn.relu(conv(self.features, 1, 1, "conv1")(x))
        r = nn.relu(conv(self.features, 3, self.stride, "conv2")(r))
        r = conv(self.features * 4, 1, 1, "conv3")(r)
        if self.stride != 1 or x.shape[-1] != self.features * 4:
            x = conv(self.features * 4, 1, self.stride,
                     "downsample")(x)
        return nn.relu(r + x)


class _ConvSkeleton(nn.Module):
    @nn.compact
    def __call__(self, x):
        x = nn.Conv(64, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
                    use_bias=False, dtype=jnp.bfloat16,
                    name="stem")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), (2, 2), padding=[(1, 1), (1, 1)])
        for i, nb in enumerate((3, 4, 6, 3)):
            for j in range(nb):
                x = _SkelBlock(64 * (2 ** i),
                               stride=2 if (j == 0 and i > 0) else 1,
                               name=f"s{i}b{j}")(x)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(1000, dtype=jnp.float32, name="fc")(x)
