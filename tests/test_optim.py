"""Golden-reference optimizer tests.

Mirrors the reference's ``tests/L0/run_optimizers/`` strategy: every
fused optimizer is asserted against the eager composition it replaces —
here torch.optim (CPU) for Adam(W)/SGD/Adagrad and hand-rolled numpy for
LAMB/NovoGrad/LARC — within dtype-appropriate tolerances.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
import torch

from apex_tpu import optim as ao

# L0 fast tier: golden kernel/state-machine tests (pytest -m l0)
pytestmark = pytest.mark.l0


def _rand_params(rng, shapes):
    return {f"p{i}": jnp.asarray(rng.normal(size=s), jnp.float32)
            for i, s in enumerate(shapes)}


def _rand_grads_like(rng, params):
    return jax.tree.map(
        lambda p: jnp.asarray(rng.normal(size=p.shape), jnp.float32), params)


SHAPES = [(4, 8), (8,), (3, 5, 2)]


def _run_jax(tx, params, grads_seq):
    state = tx.init(params)
    for g in grads_seq:
        updates, state = tx.update(g, state, params)
        params = optax.apply_updates(params, updates)
    return params


def _run_torch(make_opt, params, grads_seq):
    tparams = {k: torch.tensor(np.asarray(v), requires_grad=True)
               for k, v in params.items()}
    opt = make_opt(list(tparams.values()))
    for g in grads_seq:
        for k, tp in tparams.items():
            tp.grad = torch.tensor(np.asarray(g[k]))
        opt.step()
    return {k: jnp.asarray(v.detach().numpy()) for k, v in tparams.items()}


def _assert_trees_close(a, b, rtol=1e-5, atol=1e-6):
    jax.tree.map(lambda x, y: np.testing.assert_allclose(
        np.asarray(x), np.asarray(y), rtol=rtol, atol=atol), a, b)


class TestFusedAdam:
    @pytest.mark.parametrize("wd,adam_w", [(0.0, True), (0.01, True),
                                           (0.01, False)])
    def test_vs_torch(self, rng, wd, adam_w):
        params = _rand_params(rng, SHAPES)
        grads_seq = [_rand_grads_like(rng, params) for _ in range(5)]
        tx = ao.fused_adam(1e-2, weight_decay=wd, adam_w_mode=adam_w)
        got = _run_jax(tx, params, grads_seq)
        make = (lambda ps: torch.optim.AdamW(ps, lr=1e-2, weight_decay=wd)
                ) if adam_w else (
                lambda ps: torch.optim.Adam(ps, lr=1e-2, weight_decay=wd))
        want = _run_torch(make, params, grads_seq)
        _assert_trees_close(got, want, rtol=1e-4, atol=1e-5)

    def test_jit_single_step(self, rng):
        params = _rand_params(rng, SHAPES)
        tx = ao.fused_adam(1e-3)
        state = tx.init(params)
        g = _rand_grads_like(rng, params)

        @jax.jit
        def step(g, state, params):
            return tx.update(g, state, params)

        updates, state2 = step(g, state, params)
        assert int(state2.count) == 1
        assert jax.tree.structure(updates) == jax.tree.structure(params)

    def test_tuple_structured_params(self, rng):
        # regression: tuple pytrees must not be confused with result triples
        params = (jnp.ones((3, 3)), jnp.ones((3,)))
        grads = (jnp.full((3, 3), 0.1), jnp.full((3,), 0.1))
        tx = ao.fused_adam(1e-2)
        updates, _ = tx.update(grads, tx.init(params), params)
        assert isinstance(updates, tuple) and len(updates) == 2
        assert updates[0].shape == (3, 3) and updates[1].shape == (3,)

    def test_moment_dtype_option(self, rng):
        params = {"w": jnp.ones((4, 4), jnp.bfloat16)}
        tx = ao.fused_adam(1e-3, moment_dtype=jnp.float32)
        st = tx.init(params)
        assert st.exp_avg["w"].dtype == jnp.float32


class TestFusedAdamFP8Moments:
    """Beyond-reference fp8 block-scaled moment storage: e4m3 quanta +
    per-256-block fp32 scales, fp32 compute (BASELINE.md's
    algorithmic-traffic-reduction lever for the HBM-bound step)."""

    def test_quant_roundtrip_relative_error(self, rng):
        from apex_tpu.optim.fused_adam import (
            _fp8_dequant, _fp8_quant, _FP8_BLOCK)

        # spans many orders of magnitude across blocks — the case raw
        # e4m3 (min normal 2^-6) flushes to zero
        x = jnp.asarray(
            rng.normal(size=(4 * _FP8_BLOCK,)).astype(np.float32))
        x = x * jnp.repeat(
            jnp.asarray([1e-12, 1e-6, 1.0, 1e4], jnp.float32), _FP8_BLOCK)
        back = _fp8_dequant(_fp8_quant(x), x.shape[0])
        err = np.abs(np.asarray(back - x))
        tol = np.abs(np.asarray(x)) * 0.13 + 1e-30  # e4m3: 3-bit mantissa
        assert (err <= tol).all(), float((err / tol).max())

    def test_updates_close_to_dense(self, rng):
        params = {"w": jnp.asarray(rng.normal(size=(8, 300)),
                                   jnp.float32),
                  "b": jnp.asarray(rng.normal(size=(7,)), jnp.float32)}
        dense = ao.fused_adam(1e-2)
        fp8 = ao.fused_adam(1e-2, moment_format="fp8_block_scaled")
        sd, s8 = dense.init(params), fp8.init(params)
        for i in range(5):
            g = jax.tree.map(
                lambda p: jnp.asarray(
                    rng.normal(size=p.shape) * 1e-3, jnp.float32),
                params)
            ud, sd = dense.update(g, sd, params)
            u8, s8 = fp8.update(g, s8, params)
            for a, b in zip(jax.tree.leaves(ud), jax.tree.leaves(u8)):
                # step direction must survive the ~12% moment quant;
                # atol covers m-near-zero elements whose relative
                # error is unbounded (update ~ lr * m/sqrt(v))
                np.testing.assert_allclose(
                    np.asarray(b), np.asarray(a),
                    rtol=0.35, atol=5e-4, err_msg=f"step {i}")

    def test_trains_a_model(self, rng):
        # end-to-end: a tiny regression model reaches a loss close to
        # the dense-moment run
        w0 = jnp.asarray(rng.normal(size=(16, 1)), jnp.float32)
        x = jnp.asarray(rng.normal(size=(64, 16)), jnp.float32)
        y = x @ jnp.asarray(rng.normal(size=(16, 1)), jnp.float32)

        def run(tx):
            p = {"w": w0}
            st = tx.init(p)

            @jax.jit
            def step(p, st):
                loss, g = jax.value_and_grad(
                    lambda p: jnp.mean((x @ p["w"] - y) ** 2))(p)
                u, st2 = tx.update(g, st, p)
                return optax.apply_updates(p, u), st2, loss

            for _ in range(60):
                p, st, loss = step(p, st)
            return float(loss)

        dense_loss = run(ao.fused_adam(5e-2))
        fp8_loss = run(ao.fused_adam(
            5e-2, moment_format="fp8_block_scaled"))
        assert fp8_loss < dense_loss * 2 + 1e-3, (dense_loss, fp8_loss)

    def test_bad_format_raises(self):
        with pytest.raises(ValueError, match="moment_format"):
            ao.fused_adam(moment_format="fp4")

    @pytest.mark.parametrize("wd,adamw", [(0.0, True), (0.01, True),
                                          (0.01, False)])
    def test_pallas_kernel_matches_xla_path(self, rng, monkeypatch,
                                            wd, adamw):
        # the fused dequant-update-requant kernel (interpret mode) must
        # produce the same updates and quantized state as the XLA
        # composition, for leaves large enough to take the kernel path
        import importlib

        fa = importlib.import_module("apex_tpu.optim.fused_adam")

        n = fa._FP8_KERNEL_MIN + 300        # ragged tail rows too
        params = {"w": jnp.asarray(rng.normal(size=(n,)), jnp.float32)}
        g = {"w": jnp.asarray(rng.normal(size=(n,)) * 1e-3,
                              jnp.float32)}
        tx = ao.fused_adam(3e-3, weight_decay=wd, adam_w_mode=adamw,
                           moment_format="fp8_block_scaled")

        def run(impl):
            monkeypatch.setenv("APEX_TPU_OPS_IMPL", impl)
            st = tx.init(params)
            outs = []
            p = params
            for i in range(3):
                u, st = tx.update(g, st, p)
                p = optax.apply_updates(p, u)
                outs.append((u, st))
            return outs

        xla = run("xla")
        ker = run("pallas_interpret")
        for i, ((ux, sx), (uk, sk)) in enumerate(zip(xla, ker)):
            # not bitwise: the two compilations round differently (FMA
            # contraction) and a flipped e4m3 quantum near a rounding
            # boundary shifts later steps by ~one fp8 ulp — compare at
            # semantic tolerances instead
            np.testing.assert_allclose(
                np.asarray(uk["w"]), np.asarray(ux["w"]),
                rtol=1e-3, atol=1e-8, err_msg=f"update step {i}")
            for field in ("exp_avg", "exp_avg_sq"):
                a = getattr(sx, field)["w"]
                b = getattr(sk, field)["w"]
                da = np.asarray(a["q"].astype(jnp.float32)
                                ) * np.repeat(np.asarray(a["scale"]), 256)
                db = np.asarray(b["q"].astype(jnp.float32)
                                ) * np.repeat(np.asarray(b["scale"]), 256)
                # a flipped quantum is one e4m3 ulp of the block scale
                atol = np.repeat(np.asarray(a["scale"]), 256) * 2.0
                bad = np.abs(db - da) > 0.15 * np.abs(da) + atol
                assert not bad.any(), (
                    f"{field} dequant step {i}: {bad.sum()} elements "
                    f"beyond one-quantum tolerance")
                # block magnitudes must agree tightly
                np.testing.assert_allclose(
                    np.asarray(b["scale"]), np.asarray(a["scale"]),
                    rtol=1e-3, err_msg=f"{field} scale step {i}")

    def test_o2_apply_gradients_and_skip_step(self):
        # fp8 moment leaves must survive the full O2 path: bf16-grad
        # upcast, unscale, finiteness select (jnp.where over float8
        # leaves on overflow skip)
        from apex_tpu import amp

        params = {"w": jnp.ones((4, 300), jnp.float32)}
        st = amp.initialize(
            None, params,
            ao.fused_adam(1e-3, moment_format="fp8_block_scaled"),
            opt_level="O2", half_dtype=jnp.bfloat16)
        g = jax.tree.map(
            lambda p: jnp.full(p.shape, 1e-3, jnp.bfloat16), params)
        st2, finite = jax.jit(
            lambda s, g: s.apply_gradients(grads=g))(st, g)
        assert bool(finite)
        assert st2.opt_state.exp_avg["w"]["q"].dtype == jnp.float8_e4m3fn
        gbad = jax.tree.map(
            lambda p: jnp.full(p.shape, jnp.nan, jnp.bfloat16), params)
        st3, finite2 = jax.jit(
            lambda s, g: s.apply_gradients(grads=g))(st2, gbad)
        assert not bool(finite2)
        np.testing.assert_array_equal(
            np.asarray(st3.opt_state.exp_avg["w"]["q"].astype(
                jnp.float32)),
            np.asarray(st2.opt_state.exp_avg["w"]["q"].astype(
                jnp.float32)))


class TestFusedSGD:
    @pytest.mark.parametrize("momentum,nesterov,wd",
                             [(0.0, False, 0.0), (0.9, False, 0.0),
                              (0.9, True, 0.0), (0.9, False, 1e-4)])
    def test_vs_torch(self, rng, momentum, nesterov, wd):
        params = _rand_params(rng, SHAPES)
        grads_seq = [_rand_grads_like(rng, params) for _ in range(5)]
        tx = ao.fused_sgd(0.1, momentum=momentum, nesterov=nesterov,
                          weight_decay=wd)
        got = _run_jax(tx, params, grads_seq)
        want = _run_torch(
            lambda ps: torch.optim.SGD(ps, lr=0.1, momentum=momentum,
                                       nesterov=nesterov, weight_decay=wd),
            params, grads_seq)
        _assert_trees_close(got, want, rtol=1e-5, atol=1e-6)

    def test_nesterov_validation(self):
        with pytest.raises(ValueError):
            ao.fused_sgd(0.1, momentum=0.0, nesterov=True)


class TestFusedAdagrad:
    @pytest.mark.parametrize("wd", [0.0, 1e-3])
    def test_vs_torch(self, rng, wd):
        params = _rand_params(rng, SHAPES)
        grads_seq = [_rand_grads_like(rng, params) for _ in range(5)]
        tx = ao.fused_adagrad(0.05, weight_decay=wd)
        got = _run_jax(tx, params, grads_seq)
        want = _run_torch(
            lambda ps: torch.optim.Adagrad(ps, lr=0.05, weight_decay=wd,
                                           eps=1e-10),
            params, grads_seq)
        _assert_trees_close(got, want, rtol=1e-5, atol=1e-6)


def _numpy_lamb_reference(params, grads_seq, lr, b1, b2, eps, wd,
                          max_grad_norm):
    """Direct transcription of the documented LAMB algorithm."""
    p = {k: np.asarray(v, np.float64) for k, v in params.items()}
    m = {k: np.zeros_like(v) for k, v in p.items()}
    v = {k: np.zeros_like(vv) for k, vv in p.items()}
    t = 0
    for g in grads_seq:
        t += 1
        g = {k: np.asarray(vv, np.float64) for k, vv in g.items()}
        gnorm = np.sqrt(sum(np.sum(np.square(vv)) for vv in g.values()))
        coef = min(1.0, max_grad_norm / (gnorm + 1e-6))
        g = {k: vv * coef for k, vv in g.items()}
        bc1 = 1 - b1 ** t
        bc2 = 1 - b2 ** t
        for k in p:
            m[k] = b1 * m[k] + (1 - b1) * g[k]
            v[k] = b2 * v[k] + (1 - b2) * g[k] ** 2
            upd = (m[k] / bc1) / (np.sqrt(v[k] / bc2) + eps) + wd * p[k]
            wn = np.sqrt(np.sum(p[k] ** 2))
            un = np.sqrt(np.sum(upd ** 2))
            ratio = wn / un if (wn > 0 and un > 0) else 1.0
            p[k] = p[k] - lr * ratio * upd
    return p


class TestFusedLAMB:
    def test_vs_numpy_reference(self, rng):
        params = _rand_params(rng, SHAPES)
        grads_seq = [_rand_grads_like(rng, params) for _ in range(4)]
        kw = dict(b1=0.9, b2=0.999, eps=1e-6, weight_decay=0.01,
                  max_grad_norm=1.0)
        tx = ao.fused_lamb(0.01, **kw)
        got = _run_jax(tx, params, grads_seq)
        want = _numpy_lamb_reference(params, grads_seq, 0.01, 0.9, 0.999,
                                     1e-6, 0.01, 1.0)
        _assert_trees_close(got, want, rtol=1e-4, atol=1e-5)

    def test_no_weight_decay_skips_trust_ratio(self, rng):
        # reference semantics: trust ratio only applied when wd != 0
        # (unless always_adapt) — so wd=0 LAMB == AdamW(wd=0) modulo clip
        params = _rand_params(rng, SHAPES)
        grads_seq = [_rand_grads_like(rng, params) for _ in range(3)]
        lamb = ao.fused_lamb(1e-2, weight_decay=0.0, eps=1e-8,
                             max_grad_norm=None)
        adam = ao.fused_adam(1e-2, weight_decay=0.0, eps=1e-8)
        _assert_trees_close(_run_jax(lamb, params, grads_seq),
                            _run_jax(adam, params, grads_seq),
                            rtol=1e-5, atol=1e-6)

    def test_trust_clip(self, rng):
        params = _rand_params(rng, [(6, 6)])
        grads = [_rand_grads_like(rng, params)]
        tx = ao.fused_lamb(1e-2, weight_decay=0.01, trust_clip=True)
        _run_jax(tx, params, grads)  # smoke: compiles & runs

    def test_empty_param_tree(self):
        # regression for the batched trust-ratio norms (ISSUE 11):
        # an empty tree must not hit jnp.stack([]) at trace time
        tx = ao.fused_lamb(1e-2, weight_decay=0.01)
        state = tx.init({})
        updates, _ = tx.update({}, state, {})
        assert updates == {}


class TestFusedNovoGrad:
    def test_first_step_v_init(self, rng):
        params = _rand_params(rng, [(4, 4)])
        g = _rand_grads_like(rng, params)
        tx = ao.fused_novograd(0.01, b1=0.9, b2=0.99)
        updates, st = tx.update(g, tx.init(params), params)
        gnorm_sq = float(jnp.sum(jnp.square(g["p0"])))
        assert np.isclose(float(st.exp_avg_sq["p0"]), gnorm_sq, rtol=1e-5)
        # update = -lr * m, m = g/(sqrt(v)+eps) on first step
        want = -0.01 * (np.asarray(g["p0"]) /
                        (np.sqrt(gnorm_sq) + 1e-8))
        np.testing.assert_allclose(np.asarray(updates["p0"]), want,
                                   rtol=1e-5)

    def test_multi_step_decay(self, rng):
        params = _rand_params(rng, [(3, 3), (5,)])
        grads_seq = [_rand_grads_like(rng, params) for _ in range(4)]
        tx = ao.fused_novograd(0.01, weight_decay=0.01)
        out = _run_jax(tx, params, grads_seq)
        for k in params:
            assert not np.allclose(np.asarray(out[k]),
                                   np.asarray(params[k]))


class TestLARC:
    def test_clip_mode_scales_grads(self, rng):
        params = {"w": jnp.full((4, 4), 10.0)}
        grads = {"w": jnp.full((4, 4), 1e-4)}
        tx = ao.larc(0.1, trust_coefficient=0.02, clip=True)
        updates, _ = tx.update(grads, tx.init(params), params)
        # local_lr = 0.02*40/(0.0016...) huge -> min(local/lr,1)=1 -> unchanged
        np.testing.assert_allclose(np.asarray(updates["w"]),
                                   np.asarray(grads["w"]), rtol=1e-6)

    def test_lars_mode(self, rng):
        params = {"w": jnp.full((2, 2), 2.0)}
        grads = {"w": jnp.full((2, 2), 1.0)}
        tx = ao.larc(0.1, trust_coefficient=0.02, clip=False)
        updates, _ = tx.update(grads, tx.init(params), params)
        p_norm, g_norm = 4.0, 2.0
        local_lr = 0.02 * p_norm / (g_norm + 1e-8)
        np.testing.assert_allclose(np.asarray(updates["w"]),
                                   np.asarray(grads["w"]) * local_lr,
                                   rtol=1e-5)

    def test_zero_grad_no_adapt(self):
        params = {"w": jnp.ones((2,))}
        grads = {"w": jnp.zeros((2,))}
        tx = ao.larc(0.1)
        updates, _ = tx.update(grads, tx.init(params), params)
        np.testing.assert_array_equal(np.asarray(updates["w"]), 0.0)

    def test_chain_with_sgd(self, rng):
        params = _rand_params(rng, [(4, 4)])
        grads = [_rand_grads_like(rng, params) for _ in range(3)]
        tx = optax.chain(ao.larc(0.1), ao.fused_sgd(0.1, momentum=0.9))
        out = _run_jax(tx, params, grads)
        assert not np.allclose(np.asarray(out["p0"]),
                               np.asarray(params["p0"]))


class TestClipGrad:
    def test_clip_reduces_norm(self, rng):
        grads = _rand_grads_like(rng, _rand_params(rng, SHAPES))
        clipped, norm = ao.clip_grad_norm(grads, 0.5)
        new_norm = float(ao.tree_l2_norm(clipped))
        assert float(norm) > 0.5
        assert np.isclose(new_norm, 0.5, rtol=1e-4)

    def test_noop_when_under(self, rng):
        grads = {"g": jnp.asarray([3e-3, 4e-3])}
        clipped, norm = ao.clip_grad_norm(grads, 1.0)
        np.testing.assert_allclose(np.asarray(clipped["g"]),
                                   np.asarray(grads["g"]), rtol=1e-5)

    def test_transformation_form(self, rng):
        params = _rand_params(rng, [(4,)])
        tx = optax.chain(ao.clip_by_global_norm(1.0), ao.fused_sgd(0.1))
        g = {"p0": jnp.full((4,), 100.0)}
        updates, _ = tx.update(g, tx.init(params), params)
        # clipped to norm 1 then scaled by lr
        np.testing.assert_allclose(
            float(jnp.sqrt(jnp.sum(jnp.square(updates["p0"])))), 0.1,
            rtol=1e-4)


class TestMultiTensorHelpers:
    def test_tree_l2_norm_vs_numpy(self, rng):
        t = _rand_params(rng, SHAPES)
        want = np.sqrt(sum(np.sum(np.square(np.asarray(v)))
                           for v in t.values()))
        assert np.isclose(float(ao.tree_l2_norm(t)), want, rtol=1e-6)

    def test_per_tensor_norms(self, rng):
        t = _rand_params(rng, [(3, 3)])
        norms = ao.per_tensor_l2_norms(t)
        assert np.isclose(float(norms["p0"]),
                          np.linalg.norm(np.asarray(t["p0"])), rtol=1e-6)

    def test_tree_axpby(self):
        x = {"a": jnp.ones(3)}
        y = {"a": jnp.full(3, 2.0)}
        out = ao.tree_axpby(2.0, x, 3.0, y)
        np.testing.assert_allclose(np.asarray(out["a"]), 8.0)
