"""Regression matrix for :func:`apex_tpu.transformer.maybe_constrain`.

Round-1 verdict weak-item 6 / next-round item 10: the four-way mesh
resolution (ambient abstract mesh under trace / ambient concrete mesh /
library-global mesh / no mesh) is the most JAX-upgrade-fragile code in
the repo — this file pins each cell of the {jit, eager, shard_map,
set_mesh} x {library mesh, foreign mesh, no mesh} matrix so an upgrade
that changes tracer/mesh introspection fails loudly here, not as a
silent loss of TP sharding hints (`transformer/layers.py:53`).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu.core import mesh as mesh_lib
from apex_tpu.transformer.layers import maybe_constrain


@pytest.fixture
def tp_mesh():
    m = mesh_lib.initialize_mesh(data_parallel_size=-1,
                                 tensor_model_parallel_size=2)
    yield m
    mesh_lib.destroy_mesh()


def _x():
    return jnp.arange(16.0, dtype=jnp.float32).reshape(2, 8)


class TestNoMesh:
    def test_eager_no_mesh_is_noop(self):
        x = _x()
        y = maybe_constrain(x, None, "tensor")
        assert y is x

    def test_jit_no_mesh_is_noop(self):
        @jax.jit
        def f(x):
            return maybe_constrain(x, None, "tensor") * 1.0

        np.testing.assert_array_equal(np.asarray(f(_x())), np.asarray(_x()))


class TestLibraryGlobalMesh:
    def test_eager_constrains_to_library_mesh(self, tp_mesh):
        y = maybe_constrain(_x(), None, "tensor")
        assert y.sharding.is_equivalent_to(
            NamedSharding(tp_mesh, P(None, "tensor")), 2)

    def test_jit_constrains_to_library_mesh(self, tp_mesh):
        @jax.jit
        def f(x):
            return maybe_constrain(x, None, "tensor") + 0.0

        y = f(_x())
        assert y.sharding.is_equivalent_to(
            NamedSharding(tp_mesh, P(None, "tensor")), 2)

    def test_grad_through_constraint(self, tp_mesh):
        g = jax.grad(lambda x: jnp.sum(
            maybe_constrain(x, None, "tensor") ** 2))(_x())
        np.testing.assert_allclose(np.asarray(g), 2 * np.asarray(_x()))


class TestAmbientSetMesh:
    def test_jit_under_set_mesh_abstract_path(self, tp_mesh):
        """Inside jax.set_mesh the ambient *abstract* mesh resolves the
        constraint (the tracer branch)."""
        @jax.jit
        def f(x):
            return maybe_constrain(x, None, "tensor") + 0.0

        with jax.set_mesh(tp_mesh):
            y = f(_x())
        assert y.sharding.is_equivalent_to(
            NamedSharding(tp_mesh, P(None, "tensor")), 2)

    def test_eager_under_set_mesh_concrete_path(self, tp_mesh):
        """Eager under set_mesh: the abstract-mesh constraint form is
        illegal outside a trace — must fall through to the concrete
        path, not crash (e.g. model.init under jax.set_mesh)."""
        with jax.set_mesh(tp_mesh):
            y = maybe_constrain(_x(), None, "tensor")
        assert y.sharding.is_equivalent_to(
            NamedSharding(tp_mesh, P(None, "tensor")), 2)


class TestShardMap:
    def test_manual_axis_dropped(self, tp_mesh):
        """Inside shard_map over 'tensor', the axis is Manual — the
        constraint must degrade to a noop, not error."""
        @functools.partial(
            jax.shard_map, mesh=tp_mesh,
            in_specs=P(None, "tensor"), out_specs=P(None, "tensor"))
        def f(x):
            return maybe_constrain(x, None, "tensor") * 2.0

        np.testing.assert_array_equal(np.asarray(f(_x())),
                                      2 * np.asarray(_x()))

    def test_partial_manual_keeps_auto_axes(self, tp_mesh):
        """shard_map over 'data' only: 'tensor' stays Auto and the
        constraint on it must survive."""
        @functools.partial(
            jax.shard_map, mesh=tp_mesh, in_specs=P("data"),
            out_specs=P("data"), axis_names={"data"})
        def f(x):
            return maybe_constrain(x, None, "tensor") + 0.0

        x = jnp.arange(32.0, dtype=jnp.float32).reshape(4, 8)
        y = f(x)
        np.testing.assert_array_equal(np.asarray(y), np.asarray(x))


class TestForeignMesh:
    def test_foreign_axis_names_dropped(self):
        """A user mesh without our axis names: the spec's unknown axes
        are dropped instead of erroring."""
        devs = np.array(jax.devices()[:4]).reshape(2, 2)
        mesh = Mesh(devs, ("x", "y"))
        x = _x()
        with jax.set_mesh(mesh):
            @jax.jit
            def f(x):
                return maybe_constrain(x, None, "tensor") + 0.0

            y = f(x)
        np.testing.assert_array_equal(np.asarray(y), np.asarray(x))

    def test_mixed_foreign_and_known(self, tp_mesh):
        """Spec naming one known + one unknown axis keeps the known."""
        y = maybe_constrain(_x(), "nonexistent_axis", "tensor")
        assert y.sharding.is_equivalent_to(
            NamedSharding(tp_mesh, P(None, "tensor")), 2)


class TestDegenerateMesh:
    def test_size_one_mesh_is_noop(self):
        # single-device mesh built by hand
        mesh = Mesh(np.array(jax.devices()[:1]), ("tensor",))
        with jax.set_mesh(mesh):
            x = _x()
            y = maybe_constrain(x, None, "tensor")
        np.testing.assert_array_equal(np.asarray(y), np.asarray(x))
