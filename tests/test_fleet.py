"""Fleet router unit tier (tier-1 — NO real servers).

Everything here runs against pure functions and duck-typed fake
replicas, so the whole file costs milliseconds:

- router selection math: least-loaded by the ``blocks_in_use /
  blocks_total`` gauge (dense ``occupancy`` fallback), queue-depth tie
  break, not-ready/ejected exclusion;
- circuit-breaker transitions: healthy → suspect (K failures or a
  latency-p99 breach) → ejected → probation (cooldown) → healthy, and
  probation's fail-fast re-ejection;
- drain ordering: stop admitting → migrate actives (prompt ++
  streamed tokens, remaining budget) → shutdown, in that order;
- routing backoff: capped, deterministically jittered, and the
  retry-then-``RequestFailed`` contract (incl. the ``fleet.route`` /
  ``fleet.probe`` / ``replica.kill`` fault sites);
- autoscale decisions from queue depth + TTFT p99.

The end-to-end replica-kill/drain soaks with real ``InferenceServer``
replicas live in ``tests/test_chaos.py`` (``-m chaos``).
"""

import itertools
import time

import numpy as np
import pytest

from apex_tpu.resilience import FaultPlan, FaultSpec, active
from apex_tpu.serving import (
    FleetRouter,
    QueueFull,
    ReplicaDraining,
    RequestFailed,
    ServerClosed,
)
from apex_tpu.serving.fleet import (
    EJECTED,
    HEALTHY,
    PROBATION,
    SUSPECT,
    AutoscaleConfig,
    CircuitBreaker,
    load_score,
    route_backoff,
    scale_decision,
    select_replica,
)


class FakeServer:
    """Duck-typed ``InferenceServer``: scripted health gauges,
    recorded lifecycle calls, manually-driven token emission through
    the real tap plumbing."""

    def __init__(self, *, blocks=(0, 16), queue_depth=0, occupancy=0.0,
                 reject=None, prefix_hit=0, kv_dtype=None, kv_bits=None,
                 chips=1, mesh_shape=None):
        self.calls = []
        self.live = {}                  # key -> (prompt, kwargs, tap)
        self._keys = itertools.count()
        self.blocks_in_use, self.blocks_total = blocks
        self.queue_depth = queue_depth
        self.occupancy = occupancy
        self.reject = reject            # exception class raised on submit
        self.prefix_hit = prefix_hit    # scripted trie hit (affinity)
        self.kv_dtype = kv_dtype        # scripted pool storage dtype
        self.kv_bits = kv_bits          # ... and width (None = dense)
        self.chips = chips              # scripted chips_per_replica
        self.mesh_shape = mesh_shape    # scripted TP mesh shape
        self.running = False
        self.draining = False
        self.metrics = None
        self.metrics_interval = 32

    def prefix_hit_blocks(self, prompt):
        del prompt
        return self.prefix_hit

    # ------------------------------------------------ server surface
    def start(self, *, warmup=True):
        del warmup
        self.running = True
        self.calls.append("start")
        return self

    def health(self):
        out = {
            "status": "serving" if self.running else "stopped",
            "ready": self.running and not self.draining,
            "draining": self.draining,
            "uptime_s": 0.0,
            "queue_depth": self.queue_depth,
            "occupancy": self.occupancy,
        }
        if self.blocks_total:
            out["blocks_in_use"] = self.blocks_in_use
            out["blocks_total"] = self.blocks_total
        if self.kv_bits is not None:
            out["kv_dtype"] = self.kv_dtype
            out["kv_bits"] = self.kv_bits
        out["chips_per_replica"] = self.chips
        if self.mesh_shape:
            out["mesh_shape"] = self.mesh_shape
        return out

    def latency_summary(self):
        return {}

    def submit(self, prompt, *, max_new_tokens, tap=None, **kw):
        if self.reject is not None:
            self.calls.append("reject")
            raise self.reject("scripted rejection")
        key = next(self._keys)
        self.calls.append(("submit",
                           [int(t) for t in np.asarray(prompt).ravel()],
                           int(max_new_tokens)))
        self.live[key] = (np.asarray(prompt), kw, tap)
        return key

    def begin_drain(self):
        self.draining = True
        self.calls.append("begin_drain")
        for key in list(self.live):
            _p, _kw, tap = self.live.pop(key)
            tap(None, True, ReplicaDraining("drain eviction"))

    def kill(self, error=None):
        del error
        self.running = False
        self.calls.append("kill")
        for key in list(self.live):
            _p, _kw, tap = self.live.pop(key)
            tap(None, True, ServerClosed("killed"))

    def shutdown(self, *, wait=True, timeout=None):
        del timeout
        self.running = False
        self.calls.append(("shutdown", wait))

    # --------------------------------------------------- test driver
    def emit(self, key, token, finished=False):
        prompt, kw, tap = self.live[key]
        if finished:
            del self.live[key]
        tap(int(token), bool(finished), None)

    def submits(self):
        return [c for c in self.calls
                if isinstance(c, tuple) and c[0] == "submit"]


def _router(fakes, **kw):
    """A started router over pre-built fakes; the supervisor sleeps
    (long probe interval) so tests drive ticks deterministically."""
    kw.setdefault("probe_interval", 60.0)
    return FleetRouter(servers=fakes, **kw).start()


class TestSelectionMath:
    def test_load_score_prefers_blocks_gauge(self):
        paged = {"ready": True, "blocks_in_use": 4, "blocks_total": 16,
                 "occupancy": 1.0}
        assert load_score(paged) == 0.25     # gauge wins over occupancy
        dense = {"ready": True, "occupancy": 0.5}
        assert load_score(dense) == 0.5

    def test_least_loaded_wins(self):
        healths = [
            {"ready": True, "blocks_in_use": 8, "blocks_total": 16},
            {"ready": True, "blocks_in_use": 2, "blocks_total": 16},
            {"ready": True, "blocks_in_use": 12, "blocks_total": 16},
        ]
        assert select_replica(healths) == 1

    def test_queue_depth_breaks_ties_then_index(self):
        healths = [
            {"ready": True, "blocks_in_use": 4, "blocks_total": 16,
             "queue_depth": 3},
            {"ready": True, "blocks_in_use": 4, "blocks_total": 16,
             "queue_depth": 1},
        ]
        assert select_replica(healths) == 1
        healths[0]["queue_depth"] = 1
        assert select_replica(healths) == 0   # full tie -> stable index

    def test_not_ready_and_excluded_skipped(self):
        healths = [
            {"ready": False, "blocks_in_use": 0, "blocks_total": 16},
            None,                              # ejected/draining/dead
            {"ready": True, "blocks_in_use": 15, "blocks_total": 16},
        ]
        assert select_replica(healths) == 2
        assert select_replica([None, {"ready": False}]) == -1
        assert select_replica([]) == -1

    def test_prefix_affinity_breaks_load_ties(self):
        """ISSUE-7 satellite: equal load, the replica whose trie
        already holds the request's prefix wins — before queue depth,
        after load (affinity concentrates a hot prompt, never
        overrides least-loaded)."""
        healths = [
            {"ready": True, "blocks_in_use": 4, "blocks_total": 16,
             "queue_depth": 0},
            {"ready": True, "blocks_in_use": 4, "blocks_total": 16,
             "queue_depth": 2},
        ]
        # tie on load: affinity outranks the lower queue depth
        assert select_replica(healths, affinity=[0, 3]) == 1
        # affinity never overrides a load difference
        healths[1]["blocks_in_use"] = 8
        assert select_replica(healths, affinity=[0, 3]) == 0
        # no affinity info: pre-ISSUE-7 ordering unchanged
        healths[1]["blocks_in_use"] = 4
        assert select_replica(healths) == 0
        assert select_replica(healths, affinity=None) == 0


class TestRouteBackoff:
    def test_cap_holds_for_every_attempt(self):
        for attempt in range(1, 40):
            for uid in range(20):
                d = route_backoff(attempt, uid, base=0.01, cap=0.25)
                assert 0.0 < d <= 0.25

    def test_deterministic_and_jittered(self):
        a = route_backoff(3, uid=7)
        assert a == route_backoff(3, uid=7)      # replayable
        assert a != route_backoff(3, uid=8)      # jitter varies by uid
        assert a != route_backoff(4, uid=7)      # and by attempt
        # jitter stays within [raw/2, raw]
        raw = 0.01 * 2 ** 2
        assert raw / 2 <= a <= raw

    def test_grows_until_cap(self):
        # compare jitter-free upper envelopes
        raws = [min(0.25, 0.01 * 2 ** (a - 1)) for a in range(1, 10)]
        assert raws == sorted(raws)
        assert raws[-1] == 0.25


class TestCircuitBreaker:
    def test_k_failures_then_suspect_then_eject(self):
        br = CircuitBreaker(suspect_after=3, eject_after=2,
                            cooldown_s=1.0, probation_probes=2)
        assert br.state == HEALTHY and br.routable
        assert br.on_failure(0.0) == HEALTHY
        assert br.on_failure(0.0) == HEALTHY
        assert br.on_failure(0.0) == SUSPECT    # K = 3
        assert br.routable                      # suspect still routes
        assert br.on_failure(0.0) == SUSPECT
        assert br.on_failure(0.0) == EJECTED
        assert not br.routable

    def test_success_resets_consecutive_count(self):
        br = CircuitBreaker(suspect_after=3)
        br.on_failure(0.0)
        br.on_failure(0.0)
        br.on_success(0.0)                      # streak broken
        br.on_failure(0.0)
        br.on_failure(0.0)
        assert br.state == HEALTHY

    def test_latency_breach_suspects_immediately(self):
        br = CircuitBreaker(suspect_after=3, eject_after=2)
        assert br.on_latency_breach(0.0) == SUSPECT
        # in suspect, a breach counts like a probe failure
        assert br.on_latency_breach(0.0) == SUSPECT
        assert br.on_latency_breach(0.0) == EJECTED

    def test_cooldown_probation_readmit_and_refail(self):
        br = CircuitBreaker(suspect_after=1, eject_after=1,
                            cooldown_s=2.0, probation_probes=2)
        br.on_failure(10.0)                     # -> suspect
        br.on_failure(10.0)                     # -> ejected at t=10
        assert br.tick(11.0) == EJECTED         # cooldown not elapsed
        assert br.on_success(11.0) == EJECTED   # successes don't skip it
        assert br.tick(12.0) == PROBATION
        assert br.routable                      # on trial
        assert br.on_success(12.5) == PROBATION
        assert br.on_success(13.0) == HEALTHY   # 2 consecutive goods
        # and a probation failure re-ejects with a fresh cooldown
        br.on_failure(13.0)
        br.on_failure(13.0)
        assert br.tick(15.0) == PROBATION
        assert br.on_failure(15.5) == EJECTED
        assert br.tick(16.0) == EJECTED         # fresh cooldown from 15.5

    def test_suspect_heals_back_to_healthy(self):
        br = CircuitBreaker(suspect_after=1, probation_probes=2)
        br.on_failure(0.0)
        assert br.state == SUSPECT
        br.on_success(0.0)
        assert br.on_success(0.0) == HEALTHY

    def test_validation(self):
        with pytest.raises(ValueError):
            CircuitBreaker(suspect_after=0)
        with pytest.raises(ValueError):
            CircuitBreaker(cooldown_s=0.0)


class TestScaleDecision:
    CFG = AutoscaleConfig(scale_up_queue_depth=8,
                          scale_down_queue_depth=0,
                          ttft_slo_p99_s=1.0, min_replicas=1,
                          max_replicas=4)

    def test_queue_depth_triggers_up(self):
        assert scale_decision(9, None, 2, self.CFG) == "up"
        assert scale_decision(8, None, 2, self.CFG) is None

    def test_ttft_slo_breach_triggers_up(self):
        assert scale_decision(0, 2.0, 2, self.CFG) == "up"
        assert scale_decision(0, 0.5, 2, self.CFG) is None \
            or scale_decision(0, 0.5, 2, self.CFG) == "down"

    def test_bounds_respected(self):
        assert scale_decision(99, 9.9, 4, self.CFG) is None   # at max
        assert scale_decision(0, None, 1, self.CFG) is None   # at min
        assert scale_decision(0, None, 3, self.CFG) == "down"
        assert scale_decision(0, None, 0, self.CFG) == "up"   # below min

    def test_hysteresis_band_holds_steady(self):
        # between the down- and up-thresholds nothing changes (no flap)
        cfg = AutoscaleConfig(scale_up_queue_depth=8,
                              scale_down_queue_depth=2,
                              max_replicas=4)
        assert scale_decision(5, None, 2, cfg) is None


class TestRouting:
    def test_least_loaded_replica_gets_the_request(self):
        busy = FakeServer(blocks=(12, 16))
        idle = FakeServer(blocks=(2, 16))
        router = _router([busy, idle])
        h = router.submit([1, 2, 3], max_new_tokens=4)
        assert idle.submits() == [("submit", [1, 2, 3], 4)]
        assert busy.submits() == []
        idle.emit(0, 7)
        idle.emit(0, 9, finished=True)
        assert h.result(timeout=5) == [7, 9]
        assert router.stats()["completed"] == 1
        router.shutdown()

    def test_queue_full_fails_over_to_next_best(self):
        full = FakeServer(blocks=(0, 16), reject=QueueFull)
        backup = FakeServer(blocks=(8, 16))
        router = _router([full, backup])
        router.submit([5], max_new_tokens=2)
        assert full.calls.count("reject") >= 1
        assert backup.submits() == [("submit", [5], 2)]
        router.shutdown(wait=False)

    def test_ejected_replica_is_never_selected(self):
        a = FakeServer(blocks=(0, 16))       # least loaded...
        b = FakeServer(blocks=(9, 16))
        router = _router([a, b])
        router._replicas[0].breaker._eject(0.0)   # ...but tripped
        router.submit([4], max_new_tokens=1)
        assert a.submits() == []
        assert b.submits() == [("submit", [4], 1)]
        router.shutdown(wait=False)

    def test_prefix_affinity_routes_to_the_trie_holder(self):
        """Equal-load replicas: the one whose trie holds the request's
        prefix (``prefix_hit_blocks``) gets the request — and a loaded
        trie holder still loses to a less-loaded cold replica."""
        a = FakeServer(blocks=(4, 16))
        b = FakeServer(blocks=(4, 16), prefix_hit=2)
        router = _router([a, b])
        router.submit([7, 7, 7], max_new_tokens=1)
        assert a.submits() == []
        assert b.submits() == [("submit", [7, 7, 7], 1)]
        b.blocks_in_use = 12                 # now clearly hotter
        router.submit([7, 7, 7], max_new_tokens=1)
        assert a.submits() == [("submit", [7, 7, 7], 1)]
        router.shutdown(wait=False)

    def test_exhausted_retries_surface_request_failed(self):
        fakes = [FakeServer(reject=QueueFull) for _ in range(2)]
        router = _router(fakes, route_retries=2, backoff_base=0.001,
                         backoff_cap=0.004)
        t0 = time.monotonic()
        with pytest.raises(RequestFailed, match="routing attempts"):
            router.submit([1], max_new_tokens=1)
        # capped backoff: 3 attempts never cost more than ~3 caps
        assert time.monotonic() - t0 < 1.0
        assert router.stats()["in_flight"] == 0    # not leaked
        router.shutdown(wait=False)

    def test_route_fault_site_retries_then_succeeds(self):
        fake = FakeServer()
        router = _router([fake], backoff_base=0.001, backoff_cap=0.004)
        plan = FaultPlan([FaultSpec(site="fleet.route",
                                    kind="transient", times=1)])
        with active(plan):
            router.submit([2, 3], max_new_tokens=2)
        assert plan.fire_count(0) == 1
        assert fake.submits() == [("submit", [2, 3], 2)]
        router.shutdown(wait=False)

    def test_submit_on_stopped_fleet_raises(self):
        router = FleetRouter(servers=[FakeServer()])
        with pytest.raises(ServerClosed):
            router.submit([1], max_new_tokens=1)


class TestMigration:
    def test_kill_migrates_with_streamed_prefix(self):
        primary = FakeServer(blocks=(0, 16))
        backup = FakeServer(blocks=(8, 16))
        router = _router([primary, backup])
        h = router.submit([1, 2, 3], max_new_tokens=5)
        primary.emit(0, 11)
        primary.emit(0, 13)
        router.kill_replica(0)
        # the survivor continues from prompt ++ streamed tokens with
        # the REMAINING budget
        assert backup.submits() == [("submit", [1, 2, 3, 11, 13], 3)]
        backup.emit(0, 17)
        backup.emit(0, 19)
        backup.emit(0, 23, finished=True)
        assert h.result(timeout=5) == [11, 13, 17, 19, 23]
        stats = router.stats()
        assert stats["migrated"] == 1
        assert stats["completed"] == 1 and stats["failed"] == 0
        router.shutdown(wait=False)

    def test_migration_without_survivor_fails_explicitly(self):
        only = FakeServer()
        router = _router([only], route_retries=1, backoff_base=0.001,
                         backoff_cap=0.002)
        h = router.submit([9], max_new_tokens=3)
        only.emit(0, 5)
        router.kill_replica(0)
        with pytest.raises(RequestFailed):
            h.result(timeout=5)
        assert router.stats()["failed"] == 1
        assert router.stats()["in_flight"] == 0
        router.shutdown(wait=False)

    def test_replica_request_failed_is_terminal_not_migrated(self):
        a, b = FakeServer(blocks=(0, 16)), FakeServer(blocks=(9, 16))
        router = _router([a, b])
        h = router.submit([1], max_new_tokens=2)
        _p, _kw, tap = a.live.pop(0)
        tap(None, True, RequestFailed("deadline expired"))
        with pytest.raises(RequestFailed, match="deadline"):
            h.result(timeout=5)
        assert b.submits() == []            # no migration for failures
        assert router.stats()["migrated"] == 0
        router.shutdown(wait=False)


class TestDrainOrdering:
    def test_stop_admitting_then_migrate_then_shutdown(self):
        primary = FakeServer(blocks=(0, 16))
        backup = FakeServer(blocks=(8, 16))
        router = _router([primary, backup])
        h1 = router.submit([1, 2], max_new_tokens=4)
        h2 = router.submit([3], max_new_tokens=3)
        primary.emit(0, 7)
        assert len(primary.live) == 2 and backup.submits() == []
        drained = router.drain(0)
        assert drained is primary
        # ordering: admissions happened strictly before begin_drain,
        # and shutdown came after the drain completed
        names = [c if isinstance(c, str) else c[0]
                 for c in primary.calls]
        assert names == ["start", "submit", "submit", "begin_drain",
                         "shutdown"]
        assert primary.calls[-1] == ("shutdown", True)
        # both tenants migrated with their streamed prefixes
        assert backup.submits() == [("submit", [1, 2, 7], 3),
                                    ("submit", [3], 3)]
        # new traffic routes around the drained replica
        router.submit([8], max_new_tokens=1)
        assert backup.submits()[-1] == ("submit", [8], 1)
        backup.emit(0, 1, finished=True)
        backup.emit(1, 2, finished=True)
        backup.emit(2, 3, finished=True)
        assert h1.result(timeout=5) == [7, 1]
        assert h2.result(timeout=5) == [2]
        assert router.stats()["migrated"] == 2
        router.shutdown(wait=False)

    def test_drain_rejects_dead_or_draining_replica(self):
        fake = FakeServer()
        router = _router([fake, FakeServer()])
        router.kill_replica(0)
        with pytest.raises(ValueError, match="not live"):
            router.drain(0)
        router.shutdown(wait=False)

    def test_drain_timeout_is_retryable_not_wedging(self):
        """A drain that times out leaves the replica draining but
        recoverable: drain(index) again resumes the SAME drain (no
        second begin_drain) and completes once the tenants migrate."""
        slowpoke = FakeServer(blocks=(0, 16))
        backup = FakeServer(blocks=(8, 16))
        # begin_drain that does NOT evict yet (a replica mid-step)
        slowpoke.begin_drain = lambda: (
            setattr(slowpoke, "draining", True),
            slowpoke.calls.append("begin_drain"))
        router = _router([slowpoke, backup])
        router.submit([1, 2], max_new_tokens=3)
        with pytest.raises(TimeoutError, match="drain\\(0\\) again"):
            router.drain(0, timeout=0.05)
        # now the worker "catches up" and evicts; the retry resumes
        for key in list(slowpoke.live):
            _p, _kw, tap = slowpoke.live.pop(key)
            tap(None, True, ReplicaDraining("late eviction"))
        drained = router.drain(0)
        assert drained is slowpoke
        assert slowpoke.calls.count("begin_drain") == 1   # resumed
        assert backup.submits() == [("submit", [1, 2], 3)]
        router.shutdown(wait=False)


class TestFaultSites:
    def test_replica_kill_site_kills_one_replica(self):
        a, b = FakeServer(), FakeServer()
        router = _router([a, b])
        plan = FaultPlan([FaultSpec(site="replica.kill",
                                    kind="transient", step=0, times=1)])
        with active(plan):
            router._tick(0.0, 0)
        assert a.calls.count("kill") == 1      # first live replica
        assert b.calls.count("kill") == 0
        assert router._replicas[0].dead
        assert router.num_replicas == 1
        router.shutdown(wait=False)

    def test_probe_faults_drive_breaker_to_ejection_and_back(self):
        fake = FakeServer().start()
        router = FleetRouter(
            servers=[fake],
            breaker_factory=lambda: CircuitBreaker(
                suspect_after=2, eject_after=1, cooldown_s=1.0,
                probation_probes=1))
        breaker = router._replicas[0].breaker
        plan = FaultPlan([FaultSpec(site="fleet.probe",
                                    kind="transient", steps=(0, 1, 2))])
        with active(plan):
            router._tick(0.0, 0)
            router._tick(0.0, 1)
            assert breaker.state == SUSPECT
            router._tick(0.0, 2)
        assert breaker.state == EJECTED and not breaker.routable
        # cooldown elapses -> probation -> healthy on a clean probe
        router._tick(1.5, 3)
        assert breaker.state in (PROBATION, HEALTHY)
        router._tick(1.6, 4)
        assert breaker.state == HEALTHY

    def test_dead_worker_detected_by_probe(self):
        fake = FakeServer().start()
        router = FleetRouter(servers=[fake])

        def failed_health():
            return {"status": "failed", "ready": False,
                    "queue_depth": 0, "occupancy": 0.0}
        fake.health = failed_health
        router._tick(0.0, 0)
        assert router._replicas[0].dead


class TestAutoscale:
    def _fleet(self, cfg, n=1):
        built = []

        def factory():
            fake = FakeServer()
            fake.start()           # factory replicas join mid-flight
            built.append(fake)
            return fake
        router = FleetRouter(
            factory, replicas=n, probe_interval=60.0, autoscale=cfg)
        for rep in router._replicas:    # pre-built fakes: mark running
            rep.server.running = True
        return router, built

    def test_queue_pressure_scales_up_with_cooldown(self):
        cfg = AutoscaleConfig(scale_up_queue_depth=4,
                              scale_down_queue_depth=0,
                              max_replicas=3, cooldown_ticks=2)
        router, built = self._fleet(cfg)
        router._replicas[0].server.queue_depth = 10
        assert router.maybe_scale() == "up"
        assert router.num_replicas == 2
        # anti-flap: the next cooldown_ticks evaluations are no-ops
        assert router.maybe_scale() is None
        assert router.maybe_scale() is None
        assert router.maybe_scale() == "up"
        assert router.num_replicas == 3
        # at max_replicas the decision is suppressed entirely
        assert router.maybe_scale() is None

    def test_idle_fleet_scales_down_through_drain(self):
        cfg = AutoscaleConfig(scale_up_queue_depth=4,
                              scale_down_queue_depth=0,
                              min_replicas=1, cooldown_ticks=0)
        router, built = self._fleet(cfg, n=2)
        assert router.maybe_scale() == "down"
        assert router.num_replicas == 1
        drained = [r for r in router._replicas if r.dead]
        assert len(drained) == 1
        calls = drained[0].server.calls
        assert "begin_drain" in calls
        assert ("shutdown", True) in calls
        # floor respected
        assert router.maybe_scale() is None

    def test_scale_up_without_factory_raises(self):
        router = FleetRouter(servers=[FakeServer()])
        with pytest.raises(RuntimeError, match="factory"):
            router.scale_up()


class TestFleetHealth:
    def test_scoreboard_shape_and_ledger(self):
        a, b = FakeServer(blocks=(0, 16)), FakeServer(blocks=(4, 16))
        router = _router([a, b])
        h1 = router.submit([1], max_new_tokens=2)
        health = router.health()
        assert health["status"] == "serving" and health["ready"]
        assert health["replicas_ready"] == 2
        assert [e["breaker"] for e in health["replicas"]] \
            == [HEALTHY, HEALTHY]
        assert health["submitted"] == 1
        assert health["in_flight"] == 1
        # the ledger balances at every instant
        assert health["submitted"] == health["completed"] \
            + health["failed"] + health["in_flight"]
        a.emit(0, 3)
        a.emit(0, 4, finished=True)
        assert h1.result(timeout=5) == [3, 4]
        health = router.health()
        assert health["completed"] == 1 and health["in_flight"] == 0
        router.shutdown()
        assert not router.health()["ready"]

    def test_kv_dtype_merged_view(self):
        """ISSUE-8 fleet view: health() lists the DISTINCT pool
        storage dtypes across live replicas (a mixed fleet mid-rollout
        legitimately reports several; 'none' = unquantized paged), and
        the metrics row carries the narrowest width as
        fleet/kv_bits_min."""
        from apex_tpu.utils import MetricsWriter

        a = FakeServer(blocks=(0, 16), kv_dtype="int8", kv_bits=8)
        b = FakeServer(blocks=(0, 16), kv_dtype=None, kv_bits=32)
        c = FakeServer()                     # dense: no kv fields
        rows = []
        writer = MetricsWriter(sink=lambda s, m: rows.append((s, m)))
        router = _router([a, b, c], metrics=writer)
        health = router.health()
        assert health["kv_dtypes"] == ["int8", "none"]
        router._emit_metrics()
        merged = {}
        for _, m in rows:
            merged.update(m)
        assert merged.get("fleet/kv_bits_min") == 8.0
        router.shutdown(wait=False)

    def test_chips_merged_view(self):
        """ISSUE-13 fleet view: a replica is no longer one chip — the
        merged health() carries the widest replica
        (``chips_per_replica``), the fleet's total chip count
        (``chips_total`` = N×M capacity math), and the distinct
        per-replica mesh shapes; the metrics row mirrors the numeric
        two.  Health gauges stay per-replica, so routing and breakers
        never changed."""
        from apex_tpu.utils import MetricsWriter

        a = FakeServer(blocks=(0, 16), chips=2,
                       mesh_shape={"tensor": 2})
        b = FakeServer(blocks=(0, 16), chips=2,
                       mesh_shape={"tensor": 2})
        c = FakeServer(blocks=(0, 16))       # single-chip replica
        rows = []
        writer = MetricsWriter(sink=lambda s, m: rows.append((s, m)))
        router = _router([a, b, c], metrics=writer)
        health = router.health()
        assert health["chips_per_replica"] == 2
        assert health["chips_total"] == 5
        assert health["mesh_shapes"] == ["{'tensor': 2}"]
        router._emit_metrics()
        merged = {}
        for _, m in rows:
            merged.update(m)
        assert merged.get("fleet/chips_per_replica") == 2.0
        assert merged.get("fleet/chips_total") == 5.0
        router.shutdown(wait=False)


class TestFleetLatencySummarySnapshotRace:
    """Regression twin of the server-side fix (ISSUE 9, flagged by the
    graftlint concurrency pass): replica worker taps append to the
    router's ``_ttft`` reservoir (via ``_on_inner_token``, under
    ``_cv`` — which wraps ``_lock``) while the supervisor and clients
    snapshot it in ``latency_summary()``.  Iterating a deque during an
    append raises ``RuntimeError``; the snapshot now happens under
    ``_lock``.  The hammer fails within milliseconds unlocked."""

    def test_snapshot_survives_concurrent_tap_appends(self):
        import threading
        from collections import deque

        router = FleetRouter.__new__(FleetRouter)
        router._lock = threading.Lock()
        router._cv = threading.Condition(router._lock)
        router._ttft = deque(maxlen=4096)
        router._replicas = []               # no live replicas: p99s skip
        for i in range(512):
            with router._cv:
                router._ttft.append(0.01 * i)
        stop = threading.Event()
        errors = []

        def tap_thread():                   # _on_inner_token's append path
            i = 0
            try:
                while not stop.is_set():
                    with router._cv:
                        router._ttft.append(0.01 * (i % 11))
                    i += 1
            except BaseException as exc:    # pragma: no cover
                errors.append(exc)

        t = threading.Thread(target=tap_thread)
        t.start()
        try:
            deadline = time.monotonic() + 0.8
            while time.monotonic() < deadline:
                out = router.latency_summary()
                assert set(out) == {"ttft_p50_s", "ttft_p99_s"}
        finally:
            stop.set()
            t.join()
        assert errors == []
