"""Pipeline-parallel schedule tests.

Reference pattern (SURVEY.md §4): the pipeline schedule tests run
1F1B/interleaved on toy models and compare losses against
no-pipelining.  Here we do that hermetically on the 8-virtual-device
CPU mesh — and go further: gradients must match too (the transposed
schedule is the backward pipeline).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.core import mesh as mesh_lib
from apex_tpu.core.mesh import PIPE_AXIS
from apex_tpu.transformer import microbatches as mb_lib
from apex_tpu.transformer.pipeline_parallel import (
    forward_backward_no_pipelining,
    forward_backward_pipelining_without_interleaving,
    get_forward_backward_func,
    spmd_pipeline,
)

HID = 16
MB = 2          # microbatch size
SEQ = 4


def _stage_fn(params, x):
    """One pipeline stage: 2-layer MLP block with residual."""
    w1, b1, w2 = params
    h = jnp.tanh(x @ w1 + b1)
    return x + h @ w2


def _stacked_params(rng, pp):
    return (
        jnp.asarray(rng.normal(size=(pp, HID, HID)) * 0.3, jnp.float32),
        jnp.asarray(rng.normal(size=(pp, HID)) * 0.1, jnp.float32),
        jnp.asarray(rng.normal(size=(pp, HID, HID)) * 0.3, jnp.float32),
    )


def _sequential_reference(stacked, batch, m):
    """Ground truth: run the pp stages sequentially, no pipeline."""
    pp = stacked[0].shape[0]
    mbs = batch.reshape(m, -1, SEQ, HID)

    def full_model(stacked, x):
        for s in range(pp):
            x = _stage_fn(jax.tree.map(lambda t: t[s], stacked), x)
        return x

    def loss(stacked):
        outs = jax.vmap(lambda mb: full_model(stacked, mb))(mbs)
        return jnp.mean(outs ** 2)

    return jax.value_and_grad(loss)(stacked)


class TestPipelineSchedule:
    @pytest.mark.parametrize("m", [2, 4, 6])
    def test_matches_sequential(self, rng, mesh8, m):
        pp = mesh8.shape[PIPE_AXIS]
        stacked = _stacked_params(rng, pp)
        batch = jnp.asarray(rng.normal(size=(m * MB, SEQ, HID)),
                            jnp.float32)

        def loss_fn(y, idx):
            return jnp.mean(y ** 2)

        loss, grads = forward_backward_pipelining_without_interleaving(
            _stage_fn, loss_fn, stacked, batch, mesh=mesh8,
            num_microbatches=m)
        want_loss, want_grads = _sequential_reference(stacked, batch, m)
        np.testing.assert_allclose(float(loss), float(want_loss),
                                   rtol=1e-5)
        for g, wg in zip(jax.tree.leaves(grads),
                         jax.tree.leaves(want_grads)):
            np.testing.assert_allclose(np.asarray(g), np.asarray(wg),
                                       rtol=1e-4, atol=1e-5)

    def test_no_remat_matches(self, rng, mesh8):
        pp = mesh8.shape[PIPE_AXIS]
        stacked = _stacked_params(rng, pp)
        batch = jnp.asarray(rng.normal(size=(4 * MB, SEQ, HID)),
                            jnp.float32)

        def loss_fn(y, idx):
            return jnp.mean(y ** 2)

        l1, g1 = forward_backward_pipelining_without_interleaving(
            _stage_fn, loss_fn, stacked, batch, mesh=mesh8,
            num_microbatches=4, remat=True)
        l2, g2 = forward_backward_pipelining_without_interleaving(
            _stage_fn, loss_fn, stacked, batch, mesh=mesh8,
            num_microbatches=4, remat=False)
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
        for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)

    def test_spmd_pipeline_outputs(self, rng, mesh8):
        """Raw spmd_pipeline: outputs equal the sequential stage stack."""
        pp = mesh8.shape[PIPE_AXIS]
        stacked = _stacked_params(rng, pp)
        m = 3
        mbs = jnp.asarray(rng.normal(size=(m, MB, SEQ, HID)), jnp.float32)

        outs = jax.jit(jax.shard_map(
            lambda p, x: spmd_pipeline(_stage_fn, p, x),
            mesh=mesh8, in_specs=(P(PIPE_AXIS), P()), out_specs=P(),
            axis_names={PIPE_AXIS}))(stacked, mbs)

        want = mbs
        for s in range(pp):
            want = jax.vmap(lambda mb, s=s: _stage_fn(
                jax.tree.map(lambda t: t[s], stacked), mb))(want)
        np.testing.assert_allclose(np.asarray(outs), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)

    def test_memory_flat_in_microbatches(self, rng, mesh8):
        """The 1F1B contract (VERDICT r1 #4): peak live activation
        memory is O(pp), i.e. the compiled train step's temp buffer
        size must stay flat as M grows 4 → 32 (a transposed-scan GPipe
        grows O(M) here)."""
        pp = mesh8.shape[PIPE_AXIS]
        stacked = _stacked_params(rng, pp)

        def loss_fn(y, idx):
            return jnp.mean(y ** 2)

        def mem_stats(m):
            f = jax.jit(
                lambda p, b: forward_backward_pipelining_without_interleaving(
                    _stage_fn, loss_fn, p, b, mesh=mesh8,
                    num_microbatches=m))
            lowered = f.lower(
                jax.tree.map(
                    lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                    stacked),
                jax.ShapeDtypeStruct((m * MB, SEQ, HID), jnp.float32))
            stats = lowered.compile().memory_analysis()
            assert stats is not None
            return stats.temp_size_in_bytes, stats.argument_size_in_bytes

        (t4, a4), (t32, a32) = mem_stats(4), mem_stats(32)
        # flat in M: 8x the microbatches must not grow live memory by
        # more than a small constant (scan bookkeeping); O(M) stashing
        # would show up as ~8x
        assert t32 <= 1.5 * t4 + 4096, (t4, t32)
        # inputs are cyclically sharded over pipe + streamed by the feed
        # ring, so per-rank argument memory grows by (M2-M1)/pp
        # microbatches, not (M2-M1) (O(M) replication)
        mb_bytes = MB * SEQ * HID * 4
        pp = mesh8.shape[PIPE_AXIS]
        grown = a32 - a4
        assert grown <= 1.5 * (32 - 4) * mb_bytes / pp + 4096, (
            a4, a32, mb_bytes)

    def test_no_pipelining_accumulation(self, rng):
        params = jnp.asarray(rng.normal(size=(HID, HID)), jnp.float32)
        batch = jnp.asarray(rng.normal(size=(8, HID)), jnp.float32)

        def fwd(p, mb):
            return jnp.mean((mb @ p) ** 2)

        loss, grads = forward_backward_no_pipelining(
            fwd, batch, params, num_microbatches=4)
        want_loss, want_grads = jax.value_and_grad(
            lambda p: jnp.mean(
                jax.vmap(lambda mb: fwd(p, mb))(
                    batch.reshape(4, 2, HID))))(params)
        np.testing.assert_allclose(float(loss), float(want_loss),
                                   rtol=1e-6)
        # scan accumulation vs vmap mean: different summation order
        np.testing.assert_allclose(np.asarray(grads),
                                   np.asarray(want_grads), rtol=1e-5,
                                   atol=1e-6)

    def test_dispatch(self):
        from apex_tpu.transformer.pipeline_parallel import (
            forward_backward_pipelining_with_interleaving)
        assert get_forward_backward_func(1) is \
            forward_backward_no_pipelining
        assert get_forward_backward_func(2) is \
            forward_backward_pipelining_without_interleaving
        assert get_forward_backward_func(2, 2) is \
            forward_backward_pipelining_with_interleaving


class TestMicrobatchCalculator:
    def test_constant(self):
        mb_lib.setup_microbatch_calculator(
            global_batch_size=64, micro_batch_size=4,
            data_parallel_size=2)
        assert mb_lib.get_num_microbatches() == 8
        assert mb_lib.get_current_global_batch_size() == 64
        mb_lib.update_num_microbatches(10_000)   # no-op for constant
        assert mb_lib.get_num_microbatches() == 8
        mb_lib.destroy_microbatch_calculator()

    def test_constant_indivisible_raises(self):
        with pytest.raises(ValueError):
            mb_lib.setup_microbatch_calculator(
                global_batch_size=30, micro_batch_size=4,
                data_parallel_size=2)

    def test_rampup(self):
        # 16 -> 64 in +16 steps over 300 samples: 3 increments,
        # each spanning 100 consumed samples
        mb_lib.setup_microbatch_calculator(
            rampup_batch_size=[16, 16, 300],
            global_batch_size=64, micro_batch_size=4,
            data_parallel_size=2)
        assert mb_lib.get_current_global_batch_size() == 16
        assert mb_lib.get_num_microbatches() == 2
        mb_lib.update_num_microbatches(150)
        assert mb_lib.get_current_global_batch_size() == 32
        mb_lib.update_num_microbatches(301)
        assert mb_lib.get_current_global_batch_size() == 64
        assert mb_lib.get_num_microbatches() == 8
        mb_lib.destroy_microbatch_calculator()

    def test_uninitialized_raises(self):
        mb_lib.destroy_microbatch_calculator()
        with pytest.raises(RuntimeError):
            mb_lib.get_num_microbatches()


class TestP2P:
    def test_forward_shift(self, rng, mesh8):
        from apex_tpu.transformer.pipeline_parallel import p2p

        pp = mesh8.shape[PIPE_AXIS]
        x = jnp.arange(pp, dtype=jnp.float32)

        got = jax.jit(jax.shard_map(
            lambda v: p2p.send_forward_recv_forward(v),
            mesh=mesh8, in_specs=P(PIPE_AXIS), out_specs=P(PIPE_AXIS),
            axis_names={PIPE_AXIS}))(x)
        # rank r receives rank r-1's value (wrap)
        np.testing.assert_array_equal(np.asarray(got),
                                      np.roll(np.arange(pp), 1))


def _stacked_params_vpp(rng, v, pp):
    return (
        jnp.asarray(rng.normal(size=(v, pp, HID, HID)) * 0.3, jnp.float32),
        jnp.asarray(rng.normal(size=(v, pp, HID)) * 0.1, jnp.float32),
        jnp.asarray(rng.normal(size=(v, pp, HID, HID)) * 0.3, jnp.float32),
    )


def _sequential_reference_vpp(stacked, batch, m):
    """Ground truth for the virtual pipeline: stages in global order
    s = c*pp + r (lap-major, the Megatron chunk assignment)."""
    v, pp = stacked[0].shape[:2]
    mbs = batch.reshape(m, -1, SEQ, HID)

    def full_model(stacked, x):
        for c in range(v):
            for r in range(pp):
                x = _stage_fn(jax.tree.map(lambda t: t[c, r], stacked), x)
        return x

    def loss(stacked):
        outs = jax.vmap(lambda mb: full_model(stacked, mb))(mbs)
        return jnp.mean(outs ** 2)

    return jax.value_and_grad(loss)(stacked)


class TestInterleavedSchedule:
    @pytest.mark.parametrize("v,m", [(2, 2), (2, 4), (3, 4)])
    def test_matches_sequential(self, rng, mesh8, v, m):
        from apex_tpu.transformer.pipeline_parallel import (
            forward_backward_pipelining_with_interleaving)
        pp = mesh8.shape[PIPE_AXIS]
        stacked = _stacked_params_vpp(rng, v, pp)
        batch = jnp.asarray(rng.normal(size=(m * MB, SEQ, HID)),
                            jnp.float32)

        def loss_fn(y, idx):
            return jnp.mean(y ** 2)

        loss, grads = forward_backward_pipelining_with_interleaving(
            _stage_fn, loss_fn, stacked, batch, mesh=mesh8,
            num_microbatches=m)
        want_loss, want_grads = _sequential_reference_vpp(stacked, batch, m)
        np.testing.assert_allclose(float(loss), float(want_loss),
                                   rtol=1e-5)
        for g, wg in zip(jax.tree.leaves(grads),
                         jax.tree.leaves(want_grads)):
            np.testing.assert_allclose(np.asarray(g), np.asarray(wg),
                                       rtol=2e-4, atol=1e-5)

    @pytest.mark.parametrize("v,m", [(2, 8), (3, 4)])
    def test_matches_sequential_pp4(self, rng, v, m):
        """pp=4: the feed ring's multi-hop shift phase (up to pp-1
        consecutive hops per window) — pp=2 degenerates to one hop and
        cannot catch window-phase off-by-ones."""
        from apex_tpu.transformer.pipeline_parallel import (
            forward_backward_pipelining_with_interleaving)
        mesh = mesh_lib.initialize_mesh(pipeline_model_parallel_size=4,
                                        data_parallel_size=2)
        try:
            pp = 4
            stacked = _stacked_params_vpp(rng, v, pp)
            batch = jnp.asarray(rng.normal(size=(m * MB, SEQ, HID)),
                                jnp.float32)

            def loss_fn(y, idx):
                return jnp.mean(y ** 2)

            loss, grads = forward_backward_pipelining_with_interleaving(
                _stage_fn, loss_fn, stacked, batch, mesh=mesh,
                num_microbatches=m)
            want_loss, want_grads = _sequential_reference_vpp(
                stacked, batch, m)
            np.testing.assert_allclose(float(loss), float(want_loss),
                                       rtol=1e-5)
            for g, wg in zip(jax.tree.leaves(grads),
                             jax.tree.leaves(want_grads)):
                np.testing.assert_allclose(
                    np.asarray(g), np.asarray(wg), rtol=2e-4, atol=1e-5)
        finally:
            mesh_lib.destroy_mesh()

    def test_requires_divisible_microbatches(self, rng, mesh8):
        from apex_tpu.transformer.pipeline_parallel import (
            forward_backward_pipelining_with_interleaving)
        pp = mesh8.shape[PIPE_AXIS]
        stacked = _stacked_params_vpp(rng, 2, pp)
        batch = jnp.asarray(rng.normal(size=(3 * MB, SEQ, HID)),
                            jnp.float32)
        with pytest.raises(ValueError, match="interleaved"):
            forward_backward_pipelining_with_interleaving(
                _stage_fn, lambda y, i: jnp.mean(y ** 2), stacked,
                batch, mesh=mesh8, num_microbatches=3)

    def test_memory_flat_in_microbatches_interleaved(self, rng, mesh8):
        """Interleaved 1F1B contract: live activations O(pp·V), so the
        compiled step's temp buffers stay flat as M grows 4 → 32 (the
        autodiff circular scan would grow O(M·V))."""
        from apex_tpu.transformer.pipeline_parallel import (
            forward_backward_pipelining_with_interleaving)
        pp = mesh8.shape[PIPE_AXIS]
        stacked = _stacked_params_vpp(rng, 2, pp)

        def loss_fn(y, idx):
            return jnp.mean(y ** 2)

        def mem_stats(m):
            f = jax.jit(
                lambda p, b: forward_backward_pipelining_with_interleaving(
                    _stage_fn, loss_fn, p, b, mesh=mesh8,
                    num_microbatches=m))
            lowered = f.lower(
                jax.tree.map(
                    lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                    stacked),
                jax.ShapeDtypeStruct((m * MB, SEQ, HID), jnp.float32))
            stats = lowered.compile().memory_analysis()
            assert stats is not None
            return stats.temp_size_in_bytes, stats.argument_size_in_bytes

        (t4, a4), (t32, a32) = mem_stats(4), mem_stats(32)
        assert t32 <= 1.5 * t4 + 4096, (t4, t32)
        # inputs cyclically sharded + feed-ring streamed: per-rank
        # argument growth is (M2-M1)/pp microbatches, not (M2-M1)
        mb_bytes = MB * SEQ * HID * 4
        pp = mesh8.shape[PIPE_AXIS]
        assert a32 - a4 <= 1.5 * (32 - 4) * mb_bytes / pp + 4096, (
            a4, a32, mb_bytes)

    def test_dispatch(self):
        from apex_tpu.transformer.pipeline_parallel import (
            forward_backward_pipelining_with_interleaving)
        f = get_forward_backward_func(
            pipeline_model_parallel_size=2,
            virtual_pipeline_model_parallel_size=2)
        assert f is forward_backward_pipelining_with_interleaving
