"""Pipeline-parallel schedule tests.

Reference pattern (SURVEY.md §4): the pipeline schedule tests run
1F1B/interleaved on toy models and compare losses against
no-pipelining.  Here we do that hermetically on the 8-virtual-device
CPU mesh — and go further: gradients must match too (the transposed
schedule is the backward pipeline).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.core import mesh as mesh_lib
from apex_tpu.core.mesh import PIPE_AXIS
from apex_tpu.transformer import microbatches as mb_lib
from apex_tpu.transformer.pipeline_parallel import (
    forward_backward_no_pipelining,
    forward_backward_pipelining_without_interleaving,
    get_forward_backward_func,
    spmd_pipeline,
)

HID = 16
MB = 2          # microbatch size
SEQ = 4


def _stage_fn(params, x):
    """One pipeline stage: 2-layer MLP block with residual."""
    w1, b1, w2 = params
    h = jnp.tanh(x @ w1 + b1)
    return x + h @ w2


def _stacked_params(rng, pp):
    return (
        jnp.asarray(rng.normal(size=(pp, HID, HID)) * 0.3, jnp.float32),
        jnp.asarray(rng.normal(size=(pp, HID)) * 0.1, jnp.float32),
        jnp.asarray(rng.normal(size=(pp, HID, HID)) * 0.3, jnp.float32),
    )


def _sequential_reference(stacked, batch, m):
    """Ground truth: run the pp stages sequentially, no pipeline."""
    pp = stacked[0].shape[0]
    mbs = batch.reshape(m, -1, SEQ, HID)

    def full_model(stacked, x):
        for s in range(pp):
            x = _stage_fn(jax.tree.map(lambda t: t[s], stacked), x)
        return x

    def loss(stacked):
        outs = jax.vmap(lambda mb: full_model(stacked, mb))(mbs)
        return jnp.mean(outs ** 2)

    return jax.value_and_grad(loss)(stacked)


class TestPipelineSchedule:
    @pytest.mark.parametrize("m", [2, 4, 6])
    @pytest.mark.l0
    def test_matches_sequential(self, rng, mesh8, m):
        pp = mesh8.shape[PIPE_AXIS]
        stacked = _stacked_params(rng, pp)
        batch = jnp.asarray(rng.normal(size=(m * MB, SEQ, HID)),
                            jnp.float32)

        def loss_fn(y, idx):
            return jnp.mean(y ** 2)

        loss, grads = forward_backward_pipelining_without_interleaving(
            _stage_fn, loss_fn, stacked, batch, mesh=mesh8,
            num_microbatches=m)
        want_loss, want_grads = _sequential_reference(stacked, batch, m)
        np.testing.assert_allclose(float(loss), float(want_loss),
                                   rtol=1e-5)
        for g, wg in zip(jax.tree.leaves(grads),
                         jax.tree.leaves(want_grads)):
            np.testing.assert_allclose(np.asarray(g), np.asarray(wg),
                                       rtol=1e-4, atol=1e-5)

    def test_no_remat_matches(self, rng, mesh8):
        pp = mesh8.shape[PIPE_AXIS]
        stacked = _stacked_params(rng, pp)
        batch = jnp.asarray(rng.normal(size=(4 * MB, SEQ, HID)),
                            jnp.float32)

        def loss_fn(y, idx):
            return jnp.mean(y ** 2)

        l1, g1 = forward_backward_pipelining_without_interleaving(
            _stage_fn, loss_fn, stacked, batch, mesh=mesh8,
            num_microbatches=4, remat=True)
        l2, g2 = forward_backward_pipelining_without_interleaving(
            _stage_fn, loss_fn, stacked, batch, mesh=mesh8,
            num_microbatches=4, remat=False)
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
        for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)

    def test_spmd_pipeline_outputs(self, rng, mesh8):
        """Raw spmd_pipeline: outputs equal the sequential stage stack."""
        pp = mesh8.shape[PIPE_AXIS]
        stacked = _stacked_params(rng, pp)
        m = 3
        mbs = jnp.asarray(rng.normal(size=(m, MB, SEQ, HID)), jnp.float32)

        outs = jax.jit(jax.shard_map(
            lambda p, x: spmd_pipeline(_stage_fn, p, x),
            mesh=mesh8, in_specs=(P(PIPE_AXIS), P()), out_specs=P(),
            axis_names={PIPE_AXIS}))(stacked, mbs)

        want = mbs
        for s in range(pp):
            want = jax.vmap(lambda mb, s=s: _stage_fn(
                jax.tree.map(lambda t: t[s], stacked), mb))(want)
        np.testing.assert_allclose(np.asarray(outs), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)

    def test_memory_flat_in_microbatches(self, rng, mesh8):
        """The 1F1B contract (VERDICT r1 #4): peak live activation
        memory is O(pp), i.e. the compiled train step's temp buffer
        size must stay flat as M grows 4 → 32 (a transposed-scan GPipe
        grows O(M) here)."""
        pp = mesh8.shape[PIPE_AXIS]
        stacked = _stacked_params(rng, pp)

        def loss_fn(y, idx):
            return jnp.mean(y ** 2)

        def mem_stats(m):
            f = jax.jit(
                lambda p, b: forward_backward_pipelining_without_interleaving(
                    _stage_fn, loss_fn, p, b, mesh=mesh8,
                    num_microbatches=m))
            lowered = f.lower(
                jax.tree.map(
                    lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                    stacked),
                jax.ShapeDtypeStruct((m * MB, SEQ, HID), jnp.float32))
            stats = lowered.compile().memory_analysis()
            assert stats is not None
            return stats.temp_size_in_bytes, stats.argument_size_in_bytes

        (t4, a4), (t32, a32) = mem_stats(4), mem_stats(32)
        # flat in M: 8x the microbatches must not grow live memory by
        # more than a small constant (scan bookkeeping); O(M) stashing
        # would show up as ~8x
        assert t32 <= 1.5 * t4 + 4096, (t4, t32)
        # inputs are cyclically sharded over pipe + streamed by the feed
        # ring, so per-rank argument memory grows by (M2-M1)/pp
        # microbatches, not (M2-M1) (O(M) replication)
        mb_bytes = MB * SEQ * HID * 4
        pp = mesh8.shape[PIPE_AXIS]
        grown = a32 - a4
        assert grown <= 1.5 * (32 - 4) * mb_bytes / pp + 4096, (
            a4, a32, mb_bytes)

    def test_no_pipelining_accumulation(self, rng):
        params = jnp.asarray(rng.normal(size=(HID, HID)), jnp.float32)
        batch = jnp.asarray(rng.normal(size=(8, HID)), jnp.float32)

        def fwd(p, mb):
            return jnp.mean((mb @ p) ** 2)

        loss, grads = forward_backward_no_pipelining(
            fwd, batch, params, num_microbatches=4)
        want_loss, want_grads = jax.value_and_grad(
            lambda p: jnp.mean(
                jax.vmap(lambda mb: fwd(p, mb))(
                    batch.reshape(4, 2, HID))))(params)
        np.testing.assert_allclose(float(loss), float(want_loss),
                                   rtol=1e-6)
        # scan accumulation vs vmap mean: different summation order
        np.testing.assert_allclose(np.asarray(grads),
                                   np.asarray(want_grads), rtol=1e-5,
                                   atol=1e-6)

    def test_dispatch(self):
        from apex_tpu.transformer.pipeline_parallel import (
            forward_backward_pipelining_with_interleaving)
        assert get_forward_backward_func(1) is \
            forward_backward_no_pipelining
        assert get_forward_backward_func(2) is \
            forward_backward_pipelining_without_interleaving
        assert get_forward_backward_func(2, 2) is \
            forward_backward_pipelining_with_interleaving


class TestMicrobatchCalculator:
    def test_constant(self):
        mb_lib.setup_microbatch_calculator(
            global_batch_size=64, micro_batch_size=4,
            data_parallel_size=2)
        assert mb_lib.get_num_microbatches() == 8
        assert mb_lib.get_current_global_batch_size() == 64
        mb_lib.update_num_microbatches(10_000)   # no-op for constant
        assert mb_lib.get_num_microbatches() == 8
        mb_lib.destroy_microbatch_calculator()

    def test_constant_indivisible_raises(self):
        with pytest.raises(ValueError):
            mb_lib.setup_microbatch_calculator(
                global_batch_size=30, micro_batch_size=4,
                data_parallel_size=2)

    def test_rampup(self):
        # 16 -> 64 in +16 steps over 300 samples: 3 increments,
        # each spanning 100 consumed samples
        mb_lib.setup_microbatch_calculator(
            rampup_batch_size=[16, 16, 300],
            global_batch_size=64, micro_batch_size=4,
            data_parallel_size=2)
        assert mb_lib.get_current_global_batch_size() == 16
        assert mb_lib.get_num_microbatches() == 2
        mb_lib.update_num_microbatches(150)
        assert mb_lib.get_current_global_batch_size() == 32
        mb_lib.update_num_microbatches(301)
        assert mb_lib.get_current_global_batch_size() == 64
        assert mb_lib.get_num_microbatches() == 8
        mb_lib.destroy_microbatch_calculator()

    def test_uninitialized_raises(self):
        mb_lib.destroy_microbatch_calculator()
        with pytest.raises(RuntimeError):
            mb_lib.get_num_microbatches()


class TestP2P:
    def test_forward_shift(self, rng, mesh8):
        from apex_tpu.transformer.pipeline_parallel import p2p

        pp = mesh8.shape[PIPE_AXIS]
        x = jnp.arange(pp, dtype=jnp.float32)

        got = jax.jit(jax.shard_map(
            lambda v: p2p.send_forward_recv_forward(v),
            mesh=mesh8, in_specs=P(PIPE_AXIS), out_specs=P(PIPE_AXIS),
            axis_names={PIPE_AXIS}))(x)
        # rank r receives rank r-1's value (wrap)
        np.testing.assert_array_equal(np.asarray(got),
                                      np.roll(np.arange(pp), 1))


def _stacked_params_vpp(rng, v, pp):
    return (
        jnp.asarray(rng.normal(size=(v, pp, HID, HID)) * 0.3, jnp.float32),
        jnp.asarray(rng.normal(size=(v, pp, HID)) * 0.1, jnp.float32),
        jnp.asarray(rng.normal(size=(v, pp, HID, HID)) * 0.3, jnp.float32),
    )


def _sequential_reference_vpp(stacked, batch, m):
    """Ground truth for the virtual pipeline: stages in global order
    s = c*pp + r (lap-major, the Megatron chunk assignment)."""
    v, pp = stacked[0].shape[:2]
    mbs = batch.reshape(m, -1, SEQ, HID)

    def full_model(stacked, x):
        for c in range(v):
            for r in range(pp):
                x = _stage_fn(jax.tree.map(lambda t: t[c, r], stacked), x)
        return x

    def loss(stacked):
        outs = jax.vmap(lambda mb: full_model(stacked, mb))(mbs)
        return jnp.mean(outs ** 2)

    return jax.value_and_grad(loss)(stacked)


class TestInterleavedSchedule:
    @pytest.mark.parametrize("v,m", [(2, 2), (2, 4), (3, 4)])
    def test_matches_sequential(self, rng, mesh8, v, m):
        from apex_tpu.transformer.pipeline_parallel import (
            forward_backward_pipelining_with_interleaving)
        pp = mesh8.shape[PIPE_AXIS]
        stacked = _stacked_params_vpp(rng, v, pp)
        batch = jnp.asarray(rng.normal(size=(m * MB, SEQ, HID)),
                            jnp.float32)

        def loss_fn(y, idx):
            return jnp.mean(y ** 2)

        loss, grads = forward_backward_pipelining_with_interleaving(
            _stage_fn, loss_fn, stacked, batch, mesh=mesh8,
            num_microbatches=m)
        want_loss, want_grads = _sequential_reference_vpp(stacked, batch, m)
        np.testing.assert_allclose(float(loss), float(want_loss),
                                   rtol=1e-5)
        for g, wg in zip(jax.tree.leaves(grads),
                         jax.tree.leaves(want_grads)):
            np.testing.assert_allclose(np.asarray(g), np.asarray(wg),
                                       rtol=2e-4, atol=1e-5)

    @pytest.mark.parametrize("v,m", [(2, 8), (3, 4)])
    def test_matches_sequential_pp4(self, rng, v, m):
        """pp=4: the feed ring's multi-hop shift phase (up to pp-1
        consecutive hops per window) — pp=2 degenerates to one hop and
        cannot catch window-phase off-by-ones."""
        from apex_tpu.transformer.pipeline_parallel import (
            forward_backward_pipelining_with_interleaving)
        mesh = mesh_lib.initialize_mesh(pipeline_model_parallel_size=4,
                                        data_parallel_size=2)
        try:
            pp = 4
            stacked = _stacked_params_vpp(rng, v, pp)
            batch = jnp.asarray(rng.normal(size=(m * MB, SEQ, HID)),
                                jnp.float32)

            def loss_fn(y, idx):
                return jnp.mean(y ** 2)

            loss, grads = forward_backward_pipelining_with_interleaving(
                _stage_fn, loss_fn, stacked, batch, mesh=mesh,
                num_microbatches=m)
            want_loss, want_grads = _sequential_reference_vpp(
                stacked, batch, m)
            np.testing.assert_allclose(float(loss), float(want_loss),
                                       rtol=1e-5)
            for g, wg in zip(jax.tree.leaves(grads),
                             jax.tree.leaves(want_grads)):
                np.testing.assert_allclose(
                    np.asarray(g), np.asarray(wg), rtol=2e-4, atol=1e-5)
        finally:
            mesh_lib.destroy_mesh()

    def test_requires_divisible_microbatches(self, rng, mesh8):
        from apex_tpu.transformer.pipeline_parallel import (
            forward_backward_pipelining_with_interleaving)
        pp = mesh8.shape[PIPE_AXIS]
        stacked = _stacked_params_vpp(rng, 2, pp)
        batch = jnp.asarray(rng.normal(size=(3 * MB, SEQ, HID)),
                            jnp.float32)
        with pytest.raises(ValueError, match="interleaved"):
            forward_backward_pipelining_with_interleaving(
                _stage_fn, lambda y, i: jnp.mean(y ** 2), stacked,
                batch, mesh=mesh8, num_microbatches=3)

    def test_memory_flat_in_microbatches_interleaved(self, rng, mesh8):
        """Interleaved 1F1B contract: live activations O(pp·V), so the
        compiled step's temp buffers stay flat as M grows 4 → 32 (the
        autodiff circular scan would grow O(M·V))."""
        from apex_tpu.transformer.pipeline_parallel import (
            forward_backward_pipelining_with_interleaving)
        pp = mesh8.shape[PIPE_AXIS]
        stacked = _stacked_params_vpp(rng, 2, pp)

        def loss_fn(y, idx):
            return jnp.mean(y ** 2)

        def mem_stats(m):
            f = jax.jit(
                lambda p, b: forward_backward_pipelining_with_interleaving(
                    _stage_fn, loss_fn, p, b, mesh=mesh8,
                    num_microbatches=m))
            lowered = f.lower(
                jax.tree.map(
                    lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                    stacked),
                jax.ShapeDtypeStruct((m * MB, SEQ, HID), jnp.float32))
            stats = lowered.compile().memory_analysis()
            assert stats is not None
            return stats.temp_size_in_bytes, stats.argument_size_in_bytes

        (t4, a4), (t32, a32) = mem_stats(4), mem_stats(32)
        assert t32 <= 1.5 * t4 + 4096, (t4, t32)
        # inputs cyclically sharded + feed-ring streamed: per-rank
        # argument growth is (M2-M1)/pp microbatches, not (M2-M1)
        mb_bytes = MB * SEQ * HID * 4
        pp = mesh8.shape[PIPE_AXIS]
        assert a32 - a4 <= 1.5 * (32 - 4) * mb_bytes / pp + 4096, (
            a4, a32, mb_bytes)

    def test_dispatch(self):
        from apex_tpu.transformer.pipeline_parallel import (
            forward_backward_pipelining_with_interleaving)
        f = get_forward_backward_func(
            pipeline_model_parallel_size=2,
            virtual_pipeline_model_parallel_size=2)
        assert f is forward_backward_pipelining_with_interleaving


def _tiny_layer():
    from apex_tpu.models import TransformerConfig, ParallelTransformerLayer

    cfg = TransformerConfig(
        vocab_size=64, hidden_size=32, num_layers=1, num_heads=2,
        max_seq_len=16, causal=True)
    return ParallelTransformerLayer(cfg)


class TestBuildModel:
    """build_model parity helper (reference:
    apex/transformer/pipeline_parallel/utils.py::build_model) — stacks a
    homogeneous layer into the schedules' (pp, per_stage)/(V, pp,
    per_stage) stage layout with a matching spec tree.  Using a real
    TP layer also regression-tests the collective-safe masked tick path
    (collectives inside rank-divergent lax.cond branches deadlock; see
    schedules._unit)."""

    @pytest.mark.parametrize("v", [None, 2])
    def test_matches_sequential(self, rng, mesh8, v):
        from jax.sharding import NamedSharding
        from apex_tpu.transformer.pipeline_parallel import (
            build_model,
            forward_backward_pipelining_with_interleaving,
        )

        layer = _tiny_layer()
        x0 = jnp.zeros((MB, 8, 32), jnp.float32)
        m = 4
        batch = jnp.asarray(rng.normal(size=(m * MB, 8, 32)), jnp.float32)
        driver = (forward_backward_pipelining_without_interleaving
                  if v is None
                  else forward_backward_pipelining_with_interleaving)

        stage_fn, stacked, spec = build_model(
            layer, 4, 2, v, rng=jax.random.PRNGKey(0), sample_input=x0)
        # stage layout + spec shape: leading (pp, per_stage) (+V), pipe
        # on the stage dim, the layer's own tensor axes preserved
        lead = (2, 2) if v is None else (2, 2, 1)
        for leaf in jax.tree.leaves(stacked):
            assert leaf.shape[:len(lead)] == lead, leaf.shape
        spec_leaves = jax.tree.leaves(
            spec, is_leaf=lambda s: isinstance(s, P))
        pipe_pos = 0 if v is None else 1
        assert all(s[pipe_pos] == PIPE_AXIS for s in spec_leaves)
        assert any("tensor" in s for s in spec_leaves)

        with jax.set_mesh(mesh8):
            sharded = jax.tree.map(
                lambda s, a: jax.device_put(
                    a, NamedSharding(mesh8, s)),
                spec, stacked, is_leaf=lambda x: isinstance(x, P))
            loss, grads = jax.jit(
                lambda p, b: driver(
                    stage_fn, lambda y, i: jnp.mean(y ** 2), p, b,
                    mesh=mesh8, num_microbatches=m))(sharded, batch)
            jax.block_until_ready(grads)

        def full(p, x):
            for c in range(v or 1):
                for r in range(2):
                    sp = jax.tree.map(
                        lambda a: a[r] if v is None else a[c, r], p)
                    x = stage_fn(sp, x)
            return x

        def ref_loss(p):
            mbs = batch.reshape(m, MB, 8, 32)
            outs = jax.vmap(lambda mb: full(p, mb))(mbs)
            return jnp.mean(outs ** 2)

        want_loss, want_grads = jax.value_and_grad(ref_loss)(stacked)
        np.testing.assert_allclose(float(loss), float(want_loss),
                                   rtol=1e-5)
        for g, w in zip(jax.tree.leaves(grads),
                        jax.tree.leaves(want_grads)):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                       rtol=2e-4, atol=1e-5)

    def test_indivisible_raises(self):
        from apex_tpu.transformer.pipeline_parallel import build_model

        with pytest.raises(ValueError, match="divisible"):
            build_model(_tiny_layer(), 5, 2,
                        rng=jax.random.PRNGKey(0),
                        sample_input=jnp.zeros((2, 8, 32)))


class Test3DConvergence:
    """Multi-step convergence through the FULL 3D composed path
    (round-3 verdict item 7): build_model TP stages + 1F1B +
    loss_params/return_input_cotangents closure + FusedAdam + dynamic
    loss scaling, ~20 optimizer steps on the tp2×pp2×dp2 mesh — the
    loss must DECREASE and track the no-pipelining composition's
    trajectory.  A single-step finite-loss check cannot catch
    accumulated-state bugs (optimizer moments, loss-scale state,
    closure grads); this is the cheapest test that can."""

    def test_loss_decreases_and_tracks_reference(self, rng, mesh8):
        from jax.sharding import NamedSharding
        from apex_tpu import amp
        from apex_tpu.optim import fused_adam
        from apex_tpu.transformer.pipeline_parallel import build_model

        m, voc, seq, hid = 2, 64, 8, 32
        layer = _tiny_layer()
        x0 = jnp.zeros((MB, seq, hid), jnp.float32)
        stage_fn, stacked, spec = build_model(
            layer, 4, 2, rng=jax.random.PRNGKey(0), sample_input=x0)
        embed = jnp.asarray(rng.normal(size=(voc, hid)) * 0.3,
                            jnp.float32)
        head = jnp.asarray(rng.normal(size=(hid, voc)) * 0.3,
                           jnp.float32)
        ids = jnp.asarray(rng.integers(0, voc, size=(m * MB, seq)),
                          jnp.int32)
        labels = jnp.asarray(rng.integers(0, voc, size=(m * MB, seq)),
                             jnp.int32)
        lab_mb = labels.reshape(m, MB, seq)
        params = {"embed": embed, "head": head, "stages": stacked}
        # lr small enough for a smooth monotone-ish descent: at 5e-2
        # the trajectory is chaotic and fp roundoff between the two
        # compilations diverges the runs (measured), proving nothing
        n_steps = 20

        def loss_fn(lp, y, i):
            (hd,) = lp
            logits = y @ hd
            lab = jax.lax.dynamic_index_in_dim(
                lab_mb, jnp.clip(i, 0, m - 1), axis=0, keepdims=False)
            logp = jax.nn.log_softmax(logits)
            return -jnp.mean(
                jnp.take_along_axis(logp, lab[..., None], -1))

        def run_pipelined():
            state = amp.initialize(
                None, params, fused_adam(5e-3), opt_level="O2",
                half_dtype=jnp.float32)   # f32 compute on XLA:CPU
            with jax.set_mesh(mesh8):
                place = {"embed": P(), "head": P(), "stages": spec}
                state = state.replace(params=jax.tree.map(
                    lambda s, a: jax.device_put(
                        a, NamedSharding(mesh8, s)) if isinstance(
                            s, P) else a,
                    place, state.params,
                    is_leaf=lambda x: isinstance(x, P)))

                @jax.jit
                def step(state):
                    cp = state.policy.cast_to_compute(state.params)

                    def scaled_loss(lp, y, i):
                        return state.scale_loss(loss_fn(lp, y, i))

                    h = jnp.take(cp["embed"], ids, axis=0)
                    sloss, sgrads, aux = \
                        forward_backward_pipelining_without_interleaving(
                            stage_fn, scaled_loss, cp["stages"], h,
                            mesh=mesh8, num_microbatches=m,
                            loss_params=(cp["head"],),
                            return_input_cotangents=True)
                    cts = aux["input_cotangents"].reshape(
                        m * MB, seq, hid)
                    d_embed = jnp.zeros_like(cp["embed"]).at[ids].add(
                        cts)
                    (d_head,) = aux["loss_params_grads"]
                    grads = {"embed": d_embed, "head": d_head,
                             "stages": sgrads}
                    new_state, finite = state.apply_gradients(
                        grads=grads)
                    loss = state.loss_scaler.unscale(
                        state.loss_scale_state, sloss)
                    return new_state, loss, finite

                losses = []
                for _ in range(n_steps):
                    state, loss, finite = step(state)
                    losses.append(float(loss))
                    assert bool(finite)
            return losses

        def run_reference():
            state = amp.initialize(
                None, params, fused_adam(5e-3), opt_level="O2",
                half_dtype=jnp.float32)

            def full_loss(p):
                h = jnp.take(p["embed"], ids, axis=0).reshape(
                    m, MB, seq, hid)

                def one(mb_i, i):
                    x = mb_i
                    for r in range(2):
                        sp = jax.tree.map(lambda t: t[r], p["stages"])
                        x = stage_fn(sp, x)
                    return loss_fn((p["head"],), x, i)

                return jnp.mean(jax.vmap(one)(h, jnp.arange(m)))

            @jax.jit
            def step(state):
                def scaled(p):
                    l = full_loss(p)
                    return state.scale_loss(l), l

                grads, loss = jax.grad(scaled, has_aux=True)(
                    state.params)
                new_state, finite = state.apply_gradients(grads=grads)
                return new_state, loss, finite

            losses = []
            for _ in range(n_steps):
                state, loss, finite = step(state)
                losses.append(float(loss))
            return losses

        got = run_pipelined()
        want = run_reference()
        # converging: clearly below the start by the end
        assert got[-1] < got[0] - 0.5, got
        # and tracking the no-pipelining trajectory step for step
        np.testing.assert_allclose(got, want, rtol=5e-3, atol=5e-3)


class TestCollectiveDetection:
    """schedules auto-select computed-and-masked ticks when the stage
    or loss body traces collectives (cond-skipping would deadlock)."""

    def test_detection(self):
        from apex_tpu.transformer.pipeline_parallel.schedules import (
            _traces_collectives)
        from apex_tpu.transformer.layers import maybe_constrain

        plain = lambda p, x: x @ p
        p = jnp.ones((4, 4))
        x = jnp.ones((2, 4))
        assert not _traces_collectives(plain, p, x)
        constrained = lambda p, x: maybe_constrain(x @ p, "data", None)
        # outside a mesh maybe_constrain is a no-op -> not detected;
        # under a mesh it records a sharding_constraint
        from apex_tpu.core import mesh as mesh_lib
        m = mesh_lib.initialize_mesh(data_parallel_size=8)
        try:
            with jax.set_mesh(m):
                assert _traces_collectives(constrained, p, x)
        finally:
            mesh_lib.destroy_mesh()


class TestEmbeddingHeadClosure:
    """loss_params + return_input_cotangents close embedding/head grads
    over the 1F1B region (Megatron's stage-embedding special-casing):
    the full composed step's grads must equal plain autodiff of the
    same composition."""

    @pytest.mark.parametrize("v", [None, 2])
    def test_matches_autodiff(self, rng, mesh8, v):
        from apex_tpu.transformer.pipeline_parallel import (
            forward_backward_pipelining_with_interleaving)

        m, voc = 4, 32
        stacked = (_stacked_params(rng, 2) if v is None
                   else _stacked_params_vpp(rng, v, 2))
        driver = (forward_backward_pipelining_without_interleaving
                  if v is None
                  else forward_backward_pipelining_with_interleaving)
        embed = jnp.asarray(rng.normal(size=(voc, HID)) * 0.5,
                            jnp.float32)
        head = jnp.asarray(rng.normal(size=(HID, voc)) * 0.5,
                           jnp.float32)
        ids = jnp.asarray(rng.integers(0, voc, size=(m * MB, SEQ)),
                          jnp.int32)
        labels = jnp.asarray(rng.integers(0, voc, size=(m * MB, SEQ)),
                             jnp.int32)
        lab_mb = labels.reshape(m, MB, SEQ)

        def loss_fn(lp, y, i):
            (hd,) = lp
            logits = y @ hd
            lab = jax.lax.dynamic_index_in_dim(
                lab_mb, jnp.clip(i, 0, m - 1), axis=0, keepdims=False)
            logp = jax.nn.log_softmax(logits)
            return -jnp.mean(
                jnp.take_along_axis(logp, lab[..., None], -1))

        with jax.set_mesh(mesh8):
            def pipeline_full(stacked, embed, head):
                h = jnp.take(embed, ids, axis=0)
                loss, sgrads, aux = driver(
                    _stage_fn, loss_fn, stacked, h, mesh=mesh8,
                    num_microbatches=m, loss_params=(head,),
                    return_input_cotangents=True)
                cts = aux["input_cotangents"].reshape(m * MB, SEQ, HID)
                d_embed = jnp.zeros_like(embed).at[ids].add(cts)
                (d_head,) = aux["loss_params_grads"]
                return loss, sgrads, d_embed, d_head

            loss, sg, d_embed, d_head = jax.jit(pipeline_full)(
                stacked, embed, head)
            jax.block_until_ready(sg)

        def ref(stacked, embed, head):
            h = jnp.take(embed, ids, axis=0).reshape(m, MB, SEQ, HID)

            def one(mb_i, i):
                x = mb_i
                for c in range(v or 1):
                    for r in range(2):
                        sp = jax.tree.map(
                            lambda t: t[r] if v is None else t[c, r],
                            stacked)
                        x = _stage_fn(sp, x)
                return loss_fn((head,), x, i)

            return jnp.mean(jax.vmap(one)(h, jnp.arange(m)))

        want_loss = ref(stacked, embed, head)
        wsg, wde, wdh = jax.grad(ref, argnums=(0, 1, 2))(
            stacked, embed, head)
        np.testing.assert_allclose(float(loss), float(want_loss),
                                   rtol=1e-5)
        for g, w in zip(jax.tree.leaves(sg), jax.tree.leaves(wsg)):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                       rtol=2e-4, atol=1e-6)
        np.testing.assert_allclose(np.asarray(d_embed), np.asarray(wde),
                                   rtol=2e-4, atol=1e-6)
        np.testing.assert_allclose(np.asarray(d_head), np.asarray(wdh),
                                   rtol=2e-4, atol=1e-6)


# ===========================================================================
# apex_tpu.parallel.pipeline — the composed dp × pipe (+ ZeRO/TP) train step
# ===========================================================================
#
# The schedule-engine tests above exercise the 1F1B tick table inside
# its own single-axis driver.  The classes below test the COMPOSITION
# layer (ISSUE 20): one shard_map over {data, pipe} running the
# schedule per data replica AND the stage-local ZeRO choreography in
# the same body, against a single-device full-batch Adam reference.

from jax.sharding import Mesh, NamedSharding  # noqa: E402

from apex_tpu import amp  # noqa: E402
from apex_tpu.optim import fused_adam  # noqa: E402
from apex_tpu.parallel import ZeroConfig  # noqa: E402
from apex_tpu.parallel import pipeline as pl  # noqa: E402


def _pl_layer_apply(x, args):
    w1, b1, w2 = args
    h = jnp.tanh(x @ w1 + b1)
    return x + h @ w2, None


def _pl_stage_fn(params, x):
    # params: this stage's (L/p, ...) stacked layer slice
    x, _ = jax.lax.scan(_pl_layer_apply, x, params)
    return x


def _pl_params(seed, layers):
    r = np.random.default_rng(seed)
    return {"stages": (
        jnp.asarray(r.normal(size=(layers, HID, HID)) * 0.3, jnp.float32),
        jnp.asarray(r.normal(size=(layers, HID)) * 0.1, jnp.float32),
        jnp.asarray(r.normal(size=(layers, HID, HID)) * 0.3, jnp.float32),
    )}


def _pl_batch(seed, dp, m):
    r = np.random.default_rng(seed)
    x = jnp.asarray(r.normal(size=(dp * m, MB, HID)), jnp.float32)
    y = jnp.asarray(r.normal(size=(dp * m, MB, HID)), jnp.float32)
    return x, y


def _pl_ref_run(params, x, y, steps, lr=1e-2):
    """Single-device full-batch Adam: the ground truth the composed
    dp × pipe step must reproduce (same global batch, same optimizer)."""
    import optax

    tx = fused_adam(lr)
    opt = tx.init(params)
    xs, ys = x.reshape(-1, HID), y.reshape(-1, HID)

    @jax.jit
    def step(params, opt):
        def loss_fn(p):
            out, _ = jax.lax.scan(_pl_layer_apply, xs, p["stages"])
            return jnp.mean((out - ys) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt2 = tx.update(grads, opt, params)
        return optax.apply_updates(params, updates), opt2, loss

    losses = []
    for _ in range(steps):
        params, opt, loss = step(params, opt)
        losses.append(float(loss))
    return params, losses


def _pl_pipe_run(params, x, y, steps, *, dp, pp, lr=1e-2, zero_stage=2):
    """The composed step: stage_split -> stage_local_zero -> placed ->
    wrap_pipeline_step loop.  Returns (state, losses, trace_count)."""
    mesh = Mesh(np.array(jax.devices()[:dp * pp]).reshape(dp, pp),
                ("data", "pipe"))
    staged = {"stages": pl.stage_split(params["stages"], pp)}
    state = amp.initialize(
        None, staged, fused_adam(lr), opt_level="O0",
        zero=ZeroConfig(axis="data", axis_size=dp, stage=zero_stage))
    state = pl.stage_local_zero(state, num_stages=pp)
    state = jax.device_put(
        state, pl.pipeline_state_shardings(state, mesh=mesh))
    traces = [0]

    def body(state, mbs, labels):
        traces[0] += 1

        def loss_fn(out, i):
            yl = jax.lax.dynamic_index_in_dim(labels, i, 0,
                                              keepdims=False)
            return jnp.mean((out - yl) ** 2)

        loss, grads = pl.run_1f1b(_pl_stage_fn, loss_fn,
                                  state.params["stages"], mbs)
        grads = pl.sync_grad_overflow({"stages": grads})
        new_state, _ = state.apply_gradients(grads=grads)
        return new_state, jax.lax.pmean(loss, "data")

    step = pl.wrap_pipeline_step(body, state=state, mesh=mesh,
                                 batch_specs=(P("data"), P("data")))
    losses = []
    for _ in range(steps):
        state, loss = step(state, x, y)
        losses.append(float(loss))
    return state, losses, traces[0]


class TestBubbleMath:
    def test_bubble_fraction(self):
        assert pl.bubble_fraction(4, 8) == pytest.approx(3 / 8)
        assert pl.bubble_fraction(1, 8) == 0.0  # no pipe, no bubble

    def test_schedule_ticks_and_live(self):
        # engine tick count m + 2p - 1; live activations flat at p
        assert pl.schedule_ticks(2, 8) == 11
        assert pl.schedule_ticks(4, 4) == 11
        assert pl.live_microbatches(4) == 4

    @pytest.mark.parametrize("fn", [pl.bubble_fraction,
                                    pl.schedule_ticks])
    def test_validation(self, fn):
        with pytest.raises(ValueError):
            fn(0, 4)
        with pytest.raises(ValueError):
            fn(2, 0)


class TestStagePartition:
    def test_split_unsplit_roundtrip(self):
        tree = {"w": jnp.arange(24.0).reshape(8, 3),
                "s": jnp.float32(2.0)}
        staged = pl.stage_split(tree, 4)
        assert staged["w"].shape == (4, 2, 3)
        assert staged["s"].shape == ()          # scalars pass through
        back = pl.stage_unsplit(staged)
        np.testing.assert_array_equal(np.asarray(back["w"]),
                                      np.asarray(tree["w"]))

    def test_indivisible_raises(self):
        with pytest.raises(ValueError, match="stage-balance"):
            pl.stage_split({"w": jnp.zeros((6, 2))}, 4)

    def test_stage_specs(self):
        staged = pl.stage_split({"w": jnp.zeros((8, 3)),
                                 "s": jnp.float32(0)}, 2)
        specs = pl.stage_specs(staged)
        assert specs["w"] == P(PIPE_AXIS)
        assert specs["s"] == P()


class TestComposed1F1BStep:
    """Grads AND optimizer updates of the composed dp × pipe +
    stage-local ZeRO step match single-device Adam, at m == p (edge:
    zero steady state) and m > p."""

    @pytest.mark.parametrize("pp,m", [(2, 2), (2, 4), (4, 4), (4, 8)])
    def test_matches_single_device_adam(self, pp, m):
        dp = 2
        params = _pl_params(0, layers=4)        # divisible by both pp
        x, y = _pl_batch(1, dp, m)
        ref_params, ref_losses = _pl_ref_run(params, x, y, 3)
        state, losses, _ = _pl_pipe_run(params, x, y, 3, dp=dp, pp=pp)
        np.testing.assert_allclose(losses, ref_losses, rtol=0,
                                   atol=1e-5)
        got = pl.stage_unsplit(jax.device_get(state.params["stages"]))
        for g, w in zip(got, ref_params["stages"]):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                       rtol=0, atol=2e-6)

    def test_zero1_matches_too(self):
        params = _pl_params(2, layers=4)
        x, y = _pl_batch(3, 2, 4)
        _, ref_losses = _pl_ref_run(params, x, y, 2)
        _, losses, _ = _pl_pipe_run(params, x, y, 2, dp=2, pp=2,
                                    zero_stage=1)
        np.testing.assert_allclose(losses, ref_losses, rtol=0,
                                   atol=1e-5)

    def test_single_trace_across_steps(self):
        # the declared 1F1B budget: ONE trace covers warmup, steady
        # state and drain for the whole loop (shape-keyed executable)
        params = _pl_params(4, layers=4)
        x, y = _pl_batch(5, 2, 4)
        _, _, traces = _pl_pipe_run(params, x, y, 5, dp=2, pp=2)
        assert traces == 1


def _partial_manual_supported():
    """jax 0.4.37's shard_map fallback has no axis_names= (partial
    manual) — the pipe × tp composition needs it; skip there."""
    mesh = Mesh(np.array(jax.devices()[:2]).reshape(1, 2), ("a", "b"))
    try:
        f = jax.shard_map(lambda x: x * 2, mesh=mesh, in_specs=(P(),),
                          out_specs=P(), check_vma=False,
                          axis_names=frozenset({"a"}))
        jax.jit(f)(jnp.zeros((2,)))
        return True
    except TypeError:
        return False


class TestPipeTensorComposition:
    def test_pipe_by_tp_matches_reference(self):
        if not _partial_manual_supported():
            pytest.skip("partial-manual shard_map (axis_names=) "
                        "unsupported on this jax version")
        # data × pipe manual, tensor GSPMD-managed inside the body
        dp, pp, tp = 2, 2, 2
        mesh = Mesh(np.array(jax.devices()[:dp * pp * tp])
                    .reshape(dp, pp, tp), ("data", "pipe", "tensor"))
        params = _pl_params(6, layers=4)
        x, y = _pl_batch(7, dp, 4)
        _, ref_losses = _pl_ref_run(params, x, y, 2)
        staged = {"stages": pl.stage_split(params["stages"], pp)}
        state = amp.initialize(
            None, staged, fused_adam(1e-2), opt_level="O0",
            zero=ZeroConfig(axis="data", axis_size=dp, stage=2))
        state = pl.stage_local_zero(state, num_stages=pp)
        state = jax.device_put(
            state, pl.pipeline_state_shardings(state, mesh=mesh))

        def body(state, mbs, labels):
            def loss_fn(out, i):
                yl = jax.lax.dynamic_index_in_dim(labels, i, 0,
                                                  keepdims=False)
                return jnp.mean((out - yl) ** 2)

            loss, grads = pl.run_1f1b(_pl_stage_fn, loss_fn,
                                      state.params["stages"], mbs)
            grads = pl.sync_grad_overflow({"stages": grads})
            new_state, _ = state.apply_gradients(grads=grads)
            return new_state, jax.lax.pmean(loss, "data")

        step = pl.wrap_pipeline_step(body, state=state, mesh=mesh,
                                     batch_specs=(P("data"),
                                                  P("data")))
        losses = []
        for _ in range(2):
            state, loss = step(state, x, y)
            losses.append(float(loss))
        np.testing.assert_allclose(losses, ref_losses, rtol=0,
                                   atol=1e-5)


class TestPipelinePlacement:
    """pipeline_state_specs / pipeline_state_shardings: stage-local
    masters land P(pipe, data), stage-stacked params P(pipe), plain
    zero leaves keep the zero_state_specs convention."""

    def _state(self, pp=2, dp=2):
        params = _pl_params(8, layers=4)
        staged = {"stages": pl.stage_split(params["stages"], pp),
                  "head": {"w": jnp.zeros((HID, HID))}}
        state = amp.initialize(
            None, staged, fused_adam(1e-2), opt_level="O0",
            zero=ZeroConfig(axis="data", axis_size=dp, stage=2))
        return pl.stage_local_zero(state, num_stages=pp,
                                   staged=("stages",))

    def test_specs(self):
        state = self._state()
        specs = pl.pipeline_state_specs(state)
        assert specs.params["stages"][0] == P(PIPE_AXIS)
        assert specs.params["head"]["w"] == P()
        # stage-local (p, n, m_stage) master vs plain (n, m) master
        assert specs.opt_state.master["stages"][0] == \
            P(PIPE_AXIS, "data", None)
        assert specs.opt_state.master["head"]["w"] == P("data", None)
        assert specs.step == P()

    def test_rejects_non_zero_state(self):
        state = amp.initialize(None, {"w": jnp.zeros((4,))},
                               fused_adam(1e-2), opt_level="O0")
        with pytest.raises(ValueError, match="zero-mode"):
            pl.pipeline_state_specs(state)

    def test_placement_roundtrip(self):
        dp, pp = 2, 2
        mesh = Mesh(np.array(jax.devices()[:dp * pp]).reshape(dp, pp),
                    ("data", PIPE_AXIS))
        state = self._state(pp=pp, dp=dp)
        placed = jax.device_put(
            state, pl.pipeline_state_shardings(state, mesh=mesh))
        m = placed.opt_state.master["stages"][0]
        assert m.sharding.spec == P(PIPE_AXIS, "data", None)
        # each chip holds ONE stage's ONE data-shard of master rows
        assert m.sharding.shard_shape(m.shape)[:2] == (1, 1)
        np.testing.assert_array_equal(
            np.asarray(m),
            np.asarray(state.opt_state.master["stages"][0]))

    def test_checkpoint_restores_stage_placement(self, tmp_path):
        from apex_tpu.resilience import ResilientCheckpointer

        dp, pp = 2, 2
        mesh = Mesh(np.array(jax.devices()[:dp * pp]).reshape(dp, pp),
                    ("data", PIPE_AXIS))
        state = self._state(pp=pp, dp=dp)
        state = jax.device_put(
            state, pl.pipeline_state_shardings(state, mesh=mesh))
        ck = ResilientCheckpointer(str(tmp_path), keep=2)
        ck.save(1, state, blocking=False)
        ck.wait()
        target = self._state(pp=pp, dp=dp)
        target = jax.device_put(
            target, pl.pipeline_state_shardings(target, mesh=mesh))
        step_n, restored = ck.restore_latest(target)
        assert step_n == 1
        m = restored.opt_state.master["stages"][0]
        assert m.sharding.spec == P(PIPE_AXIS, "data", None)
        np.testing.assert_array_equal(
            np.asarray(m),
            np.asarray(state.opt_state.master["stages"][0]))


class TestSyncGradOverflow:
    def _run(self, grads):
        mesh = Mesh(np.array(jax.devices()[:2]), (PIPE_AXIS,))
        f = jax.jit(jax.shard_map(
            lambda g: pl.sync_grad_overflow({"g": g})["g"],
            mesh=mesh, in_specs=(P(PIPE_AXIS),),
            out_specs=P(PIPE_AXIS), check_vma=False))
        return np.asarray(f(grads))

    def test_any_rank_nonfinite_poisons_all(self):
        g = jnp.ones((2, 4)).at[1, 0].set(jnp.inf)  # rank 1 overflows
        out = self._run(g)
        assert not np.isfinite(out).any()       # rank 0 poisoned too

    def test_finite_grads_unchanged(self):
        g = jnp.arange(8.0).reshape(2, 4)
        np.testing.assert_array_equal(self._run(g), np.asarray(g))
