"""Pipeline-parallel schedule tests.

Reference pattern (SURVEY.md §4): the pipeline schedule tests run
1F1B/interleaved on toy models and compare losses against
no-pipelining.  Here we do that hermetically on the 8-virtual-device
CPU mesh — and go further: gradients must match too (the transposed
schedule is the backward pipeline).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.core import mesh as mesh_lib
from apex_tpu.core.mesh import PIPE_AXIS
from apex_tpu.transformer import microbatches as mb_lib
from apex_tpu.transformer.pipeline_parallel import (
    forward_backward_no_pipelining,
    forward_backward_pipelining_without_interleaving,
    get_forward_backward_func,
    spmd_pipeline,
)

HID = 16
MB = 2          # microbatch size
SEQ = 4


def _stage_fn(params, x):
    """One pipeline stage: 2-layer MLP block with residual."""
    w1, b1, w2 = params
    h = jnp.tanh(x @ w1 + b1)
    return x + h @ w2


def _stacked_params(rng, pp):
    return (
        jnp.asarray(rng.normal(size=(pp, HID, HID)) * 0.3, jnp.float32),
        jnp.asarray(rng.normal(size=(pp, HID)) * 0.1, jnp.float32),
        jnp.asarray(rng.normal(size=(pp, HID, HID)) * 0.3, jnp.float32),
    )


def _sequential_reference(stacked, batch, m):
    """Ground truth: run the pp stages sequentially, no pipeline."""
    pp = stacked[0].shape[0]
    mbs = batch.reshape(m, -1, SEQ, HID)

    def full_model(stacked, x):
        for s in range(pp):
            x = _stage_fn(jax.tree.map(lambda t: t[s], stacked), x)
        return x

    def loss(stacked):
        outs = jax.vmap(lambda mb: full_model(stacked, mb))(mbs)
        return jnp.mean(outs ** 2)

    return jax.value_and_grad(loss)(stacked)


class TestPipelineSchedule:
    @pytest.mark.parametrize("m", [2, 4, 6])
    @pytest.mark.l0
    def test_matches_sequential(self, rng, mesh8, m):
        pp = mesh8.shape[PIPE_AXIS]
        stacked = _stacked_params(rng, pp)
        batch = jnp.asarray(rng.normal(size=(m * MB, SEQ, HID)),
                            jnp.float32)

        def loss_fn(y, idx):
            return jnp.mean(y ** 2)

        loss, grads = forward_backward_pipelining_without_interleaving(
            _stage_fn, loss_fn, stacked, batch, mesh=mesh8,
            num_microbatches=m)
        want_loss, want_grads = _sequential_reference(stacked, batch, m)
        np.testing.assert_allclose(float(loss), float(want_loss),
                                   rtol=1e-5)
        for g, wg in zip(jax.tree.leaves(grads),
                         jax.tree.leaves(want_grads)):
            np.testing.assert_allclose(np.asarray(g), np.asarray(wg),
                                       rtol=1e-4, atol=1e-5)

    def test_no_remat_matches(self, rng, mesh8):
        pp = mesh8.shape[PIPE_AXIS]
        stacked = _stacked_params(rng, pp)
        batch = jnp.asarray(rng.normal(size=(4 * MB, SEQ, HID)),
                            jnp.float32)

        def loss_fn(y, idx):
            return jnp.mean(y ** 2)

        l1, g1 = forward_backward_pipelining_without_interleaving(
            _stage_fn, loss_fn, stacked, batch, mesh=mesh8,
            num_microbatches=4, remat=True)
        l2, g2 = forward_backward_pipelining_without_interleaving(
            _stage_fn, loss_fn, stacked, batch, mesh=mesh8,
            num_microbatches=4, remat=False)
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
        for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)

    def test_spmd_pipeline_outputs(self, rng, mesh8):
        """Raw spmd_pipeline: outputs equal the sequential stage stack."""
        pp = mesh8.shape[PIPE_AXIS]
        stacked = _stacked_params(rng, pp)
        m = 3
        mbs = jnp.asarray(rng.normal(size=(m, MB, SEQ, HID)), jnp.float32)

        outs = jax.jit(jax.shard_map(
            lambda p, x: spmd_pipeline(_stage_fn, p, x),
            mesh=mesh8, in_specs=(P(PIPE_AXIS), P()), out_specs=P(),
            axis_names={PIPE_AXIS}))(stacked, mbs)

        want = mbs
        for s in range(pp):
            want = jax.vmap(lambda mb, s=s: _stage_fn(
                jax.tree.map(lambda t: t[s], stacked), mb))(want)
        np.testing.assert_allclose(np.asarray(outs), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)

    def test_memory_flat_in_microbatches(self, rng, mesh8):
        """The 1F1B contract (VERDICT r1 #4): peak live activation
        memory is O(pp), i.e. the compiled train step's temp buffer
        size must stay flat as M grows 4 → 32 (a transposed-scan GPipe
        grows O(M) here)."""
        pp = mesh8.shape[PIPE_AXIS]
        stacked = _stacked_params(rng, pp)

        def loss_fn(y, idx):
            return jnp.mean(y ** 2)

        def mem_stats(m):
            f = jax.jit(
                lambda p, b: forward_backward_pipelining_without_interleaving(
                    _stage_fn, loss_fn, p, b, mesh=mesh8,
                    num_microbatches=m))
            lowered = f.lower(
                jax.tree.map(
                    lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                    stacked),
                jax.ShapeDtypeStruct((m * MB, SEQ, HID), jnp.float32))
            stats = lowered.compile().memory_analysis()
            assert stats is not None
            return stats.temp_size_in_bytes, stats.argument_size_in_bytes

        (t4, a4), (t32, a32) = mem_stats(4), mem_stats(32)
        # flat in M: 8x the microbatches must not grow live memory by
        # more than a small constant (scan bookkeeping); O(M) stashing
        # would show up as ~8x
        assert t32 <= 1.5 * t4 + 4096, (t4, t32)
        # inputs are cyclically sharded over pipe + streamed by the feed
        # ring, so per-rank argument memory grows by (M2-M1)/pp
        # microbatches, not (M2-M1) (O(M) replication)
        mb_bytes = MB * SEQ * HID * 4
        pp = mesh8.shape[PIPE_AXIS]
        grown = a32 - a4
        assert grown <= 1.5 * (32 - 4) * mb_bytes / pp + 4096, (
            a4, a32, mb_bytes)

    def test_no_pipelining_accumulation(self, rng):
        params = jnp.asarray(rng.normal(size=(HID, HID)), jnp.float32)
        batch = jnp.asarray(rng.normal(size=(8, HID)), jnp.float32)

        def fwd(p, mb):
            return jnp.mean((mb @ p) ** 2)

        loss, grads = forward_backward_no_pipelining(
            fwd, batch, params, num_microbatches=4)
        want_loss, want_grads = jax.value_and_grad(
            lambda p: jnp.mean(
                jax.vmap(lambda mb: fwd(p, mb))(
                    batch.reshape(4, 2, HID))))(params)
        np.testing.assert_allclose(float(loss), float(want_loss),
                                   rtol=1e-6)
        # scan accumulation vs vmap mean: different summation order
        np.testing.assert_allclose(np.asarray(grads),
                                   np.asarray(want_grads), rtol=1e-5,
                                   atol=1e-6)

    def test_dispatch(self):
        from apex_tpu.transformer.pipeline_parallel import (
            forward_backward_pipelining_with_interleaving)
        assert get_forward_backward_func(1) is \
            forward_backward_no_pipelining
        assert get_forward_backward_func(2) is \
            forward_backward_pipelining_without_interleaving
        assert get_forward_backward_func(2, 2) is \
            forward_backward_pipelining_with_interleaving


class TestMicrobatchCalculator:
    def test_constant(self):
        mb_lib.setup_microbatch_calculator(
            global_batch_size=64, micro_batch_size=4,
            data_parallel_size=2)
        assert mb_lib.get_num_microbatches() == 8
        assert mb_lib.get_current_global_batch_size() == 64
        mb_lib.update_num_microbatches(10_000)   # no-op for constant
        assert mb_lib.get_num_microbatches() == 8
        mb_lib.destroy_microbatch_calculator()

    def test_constant_indivisible_raises(self):
        with pytest.raises(ValueError):
            mb_lib.setup_microbatch_calculator(
                global_batch_size=30, micro_batch_size=4,
                data_parallel_size=2)

    def test_rampup(self):
        # 16 -> 64 in +16 steps over 300 samples: 3 increments,
        # each spanning 100 consumed samples
        mb_lib.setup_microbatch_calculator(
            rampup_batch_size=[16, 16, 300],
            global_batch_size=64, micro_batch_size=4,
            data_parallel_size=2)
        assert mb_lib.get_current_global_batch_size() == 16
        assert mb_lib.get_num_microbatches() == 2
        mb_lib.update_num_microbatches(150)
        assert mb_lib.get_current_global_batch_size() == 32
        mb_lib.update_num_microbatches(301)
        assert mb_lib.get_current_global_batch_size() == 64
        assert mb_lib.get_num_microbatches() == 8
        mb_lib.destroy_microbatch_calculator()

    def test_uninitialized_raises(self):
        mb_lib.destroy_microbatch_calculator()
        with pytest.raises(RuntimeError):
            mb_lib.get_num_microbatches()


class TestP2P:
    def test_forward_shift(self, rng, mesh8):
        from apex_tpu.transformer.pipeline_parallel import p2p

        pp = mesh8.shape[PIPE_AXIS]
        x = jnp.arange(pp, dtype=jnp.float32)

        got = jax.jit(jax.shard_map(
            lambda v: p2p.send_forward_recv_forward(v),
            mesh=mesh8, in_specs=P(PIPE_AXIS), out_specs=P(PIPE_AXIS),
            axis_names={PIPE_AXIS}))(x)
        # rank r receives rank r-1's value (wrap)
        np.testing.assert_array_equal(np.asarray(got),
                                      np.roll(np.arange(pp), 1))


def _stacked_params_vpp(rng, v, pp):
    return (
        jnp.asarray(rng.normal(size=(v, pp, HID, HID)) * 0.3, jnp.float32),
        jnp.asarray(rng.normal(size=(v, pp, HID)) * 0.1, jnp.float32),
        jnp.asarray(rng.normal(size=(v, pp, HID, HID)) * 0.3, jnp.float32),
    )


def _sequential_reference_vpp(stacked, batch, m):
    """Ground truth for the virtual pipeline: stages in global order
    s = c*pp + r (lap-major, the Megatron chunk assignment)."""
    v, pp = stacked[0].shape[:2]
    mbs = batch.reshape(m, -1, SEQ, HID)

    def full_model(stacked, x):
        for c in range(v):
            for r in range(pp):
                x = _stage_fn(jax.tree.map(lambda t: t[c, r], stacked), x)
        return x

    def loss(stacked):
        outs = jax.vmap(lambda mb: full_model(stacked, mb))(mbs)
        return jnp.mean(outs ** 2)

    return jax.value_and_grad(loss)(stacked)


class TestInterleavedSchedule:
    @pytest.mark.parametrize("v,m", [(2, 2), (2, 4), (3, 4)])
    def test_matches_sequential(self, rng, mesh8, v, m):
        from apex_tpu.transformer.pipeline_parallel import (
            forward_backward_pipelining_with_interleaving)
        pp = mesh8.shape[PIPE_AXIS]
        stacked = _stacked_params_vpp(rng, v, pp)
        batch = jnp.asarray(rng.normal(size=(m * MB, SEQ, HID)),
                            jnp.float32)

        def loss_fn(y, idx):
            return jnp.mean(y ** 2)

        loss, grads = forward_backward_pipelining_with_interleaving(
            _stage_fn, loss_fn, stacked, batch, mesh=mesh8,
            num_microbatches=m)
        want_loss, want_grads = _sequential_reference_vpp(stacked, batch, m)
        np.testing.assert_allclose(float(loss), float(want_loss),
                                   rtol=1e-5)
        for g, wg in zip(jax.tree.leaves(grads),
                         jax.tree.leaves(want_grads)):
            np.testing.assert_allclose(np.asarray(g), np.asarray(wg),
                                       rtol=2e-4, atol=1e-5)

    @pytest.mark.parametrize("v,m", [(2, 8), (3, 4)])
    def test_matches_sequential_pp4(self, rng, v, m):
        """pp=4: the feed ring's multi-hop shift phase (up to pp-1
        consecutive hops per window) — pp=2 degenerates to one hop and
        cannot catch window-phase off-by-ones."""
        from apex_tpu.transformer.pipeline_parallel import (
            forward_backward_pipelining_with_interleaving)
        mesh = mesh_lib.initialize_mesh(pipeline_model_parallel_size=4,
                                        data_parallel_size=2)
        try:
            pp = 4
            stacked = _stacked_params_vpp(rng, v, pp)
            batch = jnp.asarray(rng.normal(size=(m * MB, SEQ, HID)),
                                jnp.float32)

            def loss_fn(y, idx):
                return jnp.mean(y ** 2)

            loss, grads = forward_backward_pipelining_with_interleaving(
                _stage_fn, loss_fn, stacked, batch, mesh=mesh,
                num_microbatches=m)
            want_loss, want_grads = _sequential_reference_vpp(
                stacked, batch, m)
            np.testing.assert_allclose(float(loss), float(want_loss),
                                       rtol=1e-5)
            for g, wg in zip(jax.tree.leaves(grads),
                             jax.tree.leaves(want_grads)):
                np.testing.assert_allclose(
                    np.asarray(g), np.asarray(wg), rtol=2e-4, atol=1e-5)
        finally:
            mesh_lib.destroy_mesh()

    def test_requires_divisible_microbatches(self, rng, mesh8):
        from apex_tpu.transformer.pipeline_parallel import (
            forward_backward_pipelining_with_interleaving)
        pp = mesh8.shape[PIPE_AXIS]
        stacked = _stacked_params_vpp(rng, 2, pp)
        batch = jnp.asarray(rng.normal(size=(3 * MB, SEQ, HID)),
                            jnp.float32)
        with pytest.raises(ValueError, match="interleaved"):
            forward_backward_pipelining_with_interleaving(
                _stage_fn, lambda y, i: jnp.mean(y ** 2), stacked,
                batch, mesh=mesh8, num_microbatches=3)

    def test_memory_flat_in_microbatches_interleaved(self, rng, mesh8):
        """Interleaved 1F1B contract: live activations O(pp·V), so the
        compiled step's temp buffers stay flat as M grows 4 → 32 (the
        autodiff circular scan would grow O(M·V))."""
        from apex_tpu.transformer.pipeline_parallel import (
            forward_backward_pipelining_with_interleaving)
        pp = mesh8.shape[PIPE_AXIS]
        stacked = _stacked_params_vpp(rng, 2, pp)

        def loss_fn(y, idx):
            return jnp.mean(y ** 2)

        def mem_stats(m):
            f = jax.jit(
                lambda p, b: forward_backward_pipelining_with_interleaving(
                    _stage_fn, loss_fn, p, b, mesh=mesh8,
                    num_microbatches=m))
            lowered = f.lower(
                jax.tree.map(
                    lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                    stacked),
                jax.ShapeDtypeStruct((m * MB, SEQ, HID), jnp.float32))
            stats = lowered.compile().memory_analysis()
            assert stats is not None
            return stats.temp_size_in_bytes, stats.argument_size_in_bytes

        (t4, a4), (t32, a32) = mem_stats(4), mem_stats(32)
        assert t32 <= 1.5 * t4 + 4096, (t4, t32)
        # inputs cyclically sharded + feed-ring streamed: per-rank
        # argument growth is (M2-M1)/pp microbatches, not (M2-M1)
        mb_bytes = MB * SEQ * HID * 4
        pp = mesh8.shape[PIPE_AXIS]
        assert a32 - a4 <= 1.5 * (32 - 4) * mb_bytes / pp + 4096, (
            a4, a32, mb_bytes)

    def test_dispatch(self):
        from apex_tpu.transformer.pipeline_parallel import (
            forward_backward_pipelining_with_interleaving)
        f = get_forward_backward_func(
            pipeline_model_parallel_size=2,
            virtual_pipeline_model_parallel_size=2)
        assert f is forward_backward_pipelining_with_interleaving


def _tiny_layer():
    from apex_tpu.models import TransformerConfig, ParallelTransformerLayer

    cfg = TransformerConfig(
        vocab_size=64, hidden_size=32, num_layers=1, num_heads=2,
        max_seq_len=16, causal=True)
    return ParallelTransformerLayer(cfg)


class TestBuildModel:
    """build_model parity helper (reference:
    apex/transformer/pipeline_parallel/utils.py::build_model) — stacks a
    homogeneous layer into the schedules' (pp, per_stage)/(V, pp,
    per_stage) stage layout with a matching spec tree.  Using a real
    TP layer also regression-tests the collective-safe masked tick path
    (collectives inside rank-divergent lax.cond branches deadlock; see
    schedules._unit)."""

    @pytest.mark.parametrize("v", [None, 2])
    def test_matches_sequential(self, rng, mesh8, v):
        from jax.sharding import NamedSharding
        from apex_tpu.transformer.pipeline_parallel import (
            build_model,
            forward_backward_pipelining_with_interleaving,
        )

        layer = _tiny_layer()
        x0 = jnp.zeros((MB, 8, 32), jnp.float32)
        m = 4
        batch = jnp.asarray(rng.normal(size=(m * MB, 8, 32)), jnp.float32)
        driver = (forward_backward_pipelining_without_interleaving
                  if v is None
                  else forward_backward_pipelining_with_interleaving)

        stage_fn, stacked, spec = build_model(
            layer, 4, 2, v, rng=jax.random.PRNGKey(0), sample_input=x0)
        # stage layout + spec shape: leading (pp, per_stage) (+V), pipe
        # on the stage dim, the layer's own tensor axes preserved
        lead = (2, 2) if v is None else (2, 2, 1)
        for leaf in jax.tree.leaves(stacked):
            assert leaf.shape[:len(lead)] == lead, leaf.shape
        spec_leaves = jax.tree.leaves(
            spec, is_leaf=lambda s: isinstance(s, P))
        pipe_pos = 0 if v is None else 1
        assert all(s[pipe_pos] == PIPE_AXIS for s in spec_leaves)
        assert any("tensor" in s for s in spec_leaves)

        with jax.set_mesh(mesh8):
            sharded = jax.tree.map(
                lambda s, a: jax.device_put(
                    a, NamedSharding(mesh8, s)),
                spec, stacked, is_leaf=lambda x: isinstance(x, P))
            loss, grads = jax.jit(
                lambda p, b: driver(
                    stage_fn, lambda y, i: jnp.mean(y ** 2), p, b,
                    mesh=mesh8, num_microbatches=m))(sharded, batch)
            jax.block_until_ready(grads)

        def full(p, x):
            for c in range(v or 1):
                for r in range(2):
                    sp = jax.tree.map(
                        lambda a: a[r] if v is None else a[c, r], p)
                    x = stage_fn(sp, x)
            return x

        def ref_loss(p):
            mbs = batch.reshape(m, MB, 8, 32)
            outs = jax.vmap(lambda mb: full(p, mb))(mbs)
            return jnp.mean(outs ** 2)

        want_loss, want_grads = jax.value_and_grad(ref_loss)(stacked)
        np.testing.assert_allclose(float(loss), float(want_loss),
                                   rtol=1e-5)
        for g, w in zip(jax.tree.leaves(grads),
                        jax.tree.leaves(want_grads)):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                       rtol=2e-4, atol=1e-5)

    def test_indivisible_raises(self):
        from apex_tpu.transformer.pipeline_parallel import build_model

        with pytest.raises(ValueError, match="divisible"):
            build_model(_tiny_layer(), 5, 2,
                        rng=jax.random.PRNGKey(0),
                        sample_input=jnp.zeros((2, 8, 32)))


class Test3DConvergence:
    """Multi-step convergence through the FULL 3D composed path
    (round-3 verdict item 7): build_model TP stages + 1F1B +
    loss_params/return_input_cotangents closure + FusedAdam + dynamic
    loss scaling, ~20 optimizer steps on the tp2×pp2×dp2 mesh — the
    loss must DECREASE and track the no-pipelining composition's
    trajectory.  A single-step finite-loss check cannot catch
    accumulated-state bugs (optimizer moments, loss-scale state,
    closure grads); this is the cheapest test that can."""

    def test_loss_decreases_and_tracks_reference(self, rng, mesh8):
        from jax.sharding import NamedSharding
        from apex_tpu import amp
        from apex_tpu.optim import fused_adam
        from apex_tpu.transformer.pipeline_parallel import build_model

        m, voc, seq, hid = 2, 64, 8, 32
        layer = _tiny_layer()
        x0 = jnp.zeros((MB, seq, hid), jnp.float32)
        stage_fn, stacked, spec = build_model(
            layer, 4, 2, rng=jax.random.PRNGKey(0), sample_input=x0)
        embed = jnp.asarray(rng.normal(size=(voc, hid)) * 0.3,
                            jnp.float32)
        head = jnp.asarray(rng.normal(size=(hid, voc)) * 0.3,
                           jnp.float32)
        ids = jnp.asarray(rng.integers(0, voc, size=(m * MB, seq)),
                          jnp.int32)
        labels = jnp.asarray(rng.integers(0, voc, size=(m * MB, seq)),
                             jnp.int32)
        lab_mb = labels.reshape(m, MB, seq)
        params = {"embed": embed, "head": head, "stages": stacked}
        # lr small enough for a smooth monotone-ish descent: at 5e-2
        # the trajectory is chaotic and fp roundoff between the two
        # compilations diverges the runs (measured), proving nothing
        n_steps = 20

        def loss_fn(lp, y, i):
            (hd,) = lp
            logits = y @ hd
            lab = jax.lax.dynamic_index_in_dim(
                lab_mb, jnp.clip(i, 0, m - 1), axis=0, keepdims=False)
            logp = jax.nn.log_softmax(logits)
            return -jnp.mean(
                jnp.take_along_axis(logp, lab[..., None], -1))

        def run_pipelined():
            state = amp.initialize(
                None, params, fused_adam(5e-3), opt_level="O2",
                half_dtype=jnp.float32)   # f32 compute on XLA:CPU
            with jax.set_mesh(mesh8):
                place = {"embed": P(), "head": P(), "stages": spec}
                state = state.replace(params=jax.tree.map(
                    lambda s, a: jax.device_put(
                        a, NamedSharding(mesh8, s)) if isinstance(
                            s, P) else a,
                    place, state.params,
                    is_leaf=lambda x: isinstance(x, P)))

                @jax.jit
                def step(state):
                    cp = state.policy.cast_to_compute(state.params)

                    def scaled_loss(lp, y, i):
                        return state.scale_loss(loss_fn(lp, y, i))

                    h = jnp.take(cp["embed"], ids, axis=0)
                    sloss, sgrads, aux = \
                        forward_backward_pipelining_without_interleaving(
                            stage_fn, scaled_loss, cp["stages"], h,
                            mesh=mesh8, num_microbatches=m,
                            loss_params=(cp["head"],),
                            return_input_cotangents=True)
                    cts = aux["input_cotangents"].reshape(
                        m * MB, seq, hid)
                    d_embed = jnp.zeros_like(cp["embed"]).at[ids].add(
                        cts)
                    (d_head,) = aux["loss_params_grads"]
                    grads = {"embed": d_embed, "head": d_head,
                             "stages": sgrads}
                    new_state, finite = state.apply_gradients(
                        grads=grads)
                    loss = state.loss_scaler.unscale(
                        state.loss_scale_state, sloss)
                    return new_state, loss, finite

                losses = []
                for _ in range(n_steps):
                    state, loss, finite = step(state)
                    losses.append(float(loss))
                    assert bool(finite)
            return losses

        def run_reference():
            state = amp.initialize(
                None, params, fused_adam(5e-3), opt_level="O2",
                half_dtype=jnp.float32)

            def full_loss(p):
                h = jnp.take(p["embed"], ids, axis=0).reshape(
                    m, MB, seq, hid)

                def one(mb_i, i):
                    x = mb_i
                    for r in range(2):
                        sp = jax.tree.map(lambda t: t[r], p["stages"])
                        x = stage_fn(sp, x)
                    return loss_fn((p["head"],), x, i)

                return jnp.mean(jax.vmap(one)(h, jnp.arange(m)))

            @jax.jit
            def step(state):
                def scaled(p):
                    l = full_loss(p)
                    return state.scale_loss(l), l

                grads, loss = jax.grad(scaled, has_aux=True)(
                    state.params)
                new_state, finite = state.apply_gradients(grads=grads)
                return new_state, loss, finite

            losses = []
            for _ in range(n_steps):
                state, loss, finite = step(state)
                losses.append(float(loss))
            return losses

        got = run_pipelined()
        want = run_reference()
        # converging: clearly below the start by the end
        assert got[-1] < got[0] - 0.5, got
        # and tracking the no-pipelining trajectory step for step
        np.testing.assert_allclose(got, want, rtol=5e-3, atol=5e-3)


class TestCollectiveDetection:
    """schedules auto-select computed-and-masked ticks when the stage
    or loss body traces collectives (cond-skipping would deadlock)."""

    def test_detection(self):
        from apex_tpu.transformer.pipeline_parallel.schedules import (
            _traces_collectives)
        from apex_tpu.transformer.layers import maybe_constrain

        plain = lambda p, x: x @ p
        p = jnp.ones((4, 4))
        x = jnp.ones((2, 4))
        assert not _traces_collectives(plain, p, x)
        constrained = lambda p, x: maybe_constrain(x @ p, "data", None)
        # outside a mesh maybe_constrain is a no-op -> not detected;
        # under a mesh it records a sharding_constraint
        from apex_tpu.core import mesh as mesh_lib
        m = mesh_lib.initialize_mesh(data_parallel_size=8)
        try:
            with jax.set_mesh(m):
                assert _traces_collectives(constrained, p, x)
        finally:
            mesh_lib.destroy_mesh()


class TestEmbeddingHeadClosure:
    """loss_params + return_input_cotangents close embedding/head grads
    over the 1F1B region (Megatron's stage-embedding special-casing):
    the full composed step's grads must equal plain autodiff of the
    same composition."""

    @pytest.mark.parametrize("v", [None, 2])
    def test_matches_autodiff(self, rng, mesh8, v):
        from apex_tpu.transformer.pipeline_parallel import (
            forward_backward_pipelining_with_interleaving)

        m, voc = 4, 32
        stacked = (_stacked_params(rng, 2) if v is None
                   else _stacked_params_vpp(rng, v, 2))
        driver = (forward_backward_pipelining_without_interleaving
                  if v is None
                  else forward_backward_pipelining_with_interleaving)
        embed = jnp.asarray(rng.normal(size=(voc, HID)) * 0.5,
                            jnp.float32)
        head = jnp.asarray(rng.normal(size=(HID, voc)) * 0.5,
                           jnp.float32)
        ids = jnp.asarray(rng.integers(0, voc, size=(m * MB, SEQ)),
                          jnp.int32)
        labels = jnp.asarray(rng.integers(0, voc, size=(m * MB, SEQ)),
                             jnp.int32)
        lab_mb = labels.reshape(m, MB, SEQ)

        def loss_fn(lp, y, i):
            (hd,) = lp
            logits = y @ hd
            lab = jax.lax.dynamic_index_in_dim(
                lab_mb, jnp.clip(i, 0, m - 1), axis=0, keepdims=False)
            logp = jax.nn.log_softmax(logits)
            return -jnp.mean(
                jnp.take_along_axis(logp, lab[..., None], -1))

        with jax.set_mesh(mesh8):
            def pipeline_full(stacked, embed, head):
                h = jnp.take(embed, ids, axis=0)
                loss, sgrads, aux = driver(
                    _stage_fn, loss_fn, stacked, h, mesh=mesh8,
                    num_microbatches=m, loss_params=(head,),
                    return_input_cotangents=True)
                cts = aux["input_cotangents"].reshape(m * MB, SEQ, HID)
                d_embed = jnp.zeros_like(embed).at[ids].add(cts)
                (d_head,) = aux["loss_params_grads"]
                return loss, sgrads, d_embed, d_head

            loss, sg, d_embed, d_head = jax.jit(pipeline_full)(
                stacked, embed, head)
            jax.block_until_ready(sg)

        def ref(stacked, embed, head):
            h = jnp.take(embed, ids, axis=0).reshape(m, MB, SEQ, HID)

            def one(mb_i, i):
                x = mb_i
                for c in range(v or 1):
                    for r in range(2):
                        sp = jax.tree.map(
                            lambda t: t[r] if v is None else t[c, r],
                            stacked)
                        x = _stage_fn(sp, x)
                return loss_fn((head,), x, i)

            return jnp.mean(jax.vmap(one)(h, jnp.arange(m)))

        want_loss = ref(stacked, embed, head)
        wsg, wde, wdh = jax.grad(ref, argnums=(0, 1, 2))(
            stacked, embed, head)
        np.testing.assert_allclose(float(loss), float(want_loss),
                                   rtol=1e-5)
        for g, w in zip(jax.tree.leaves(sg), jax.tree.leaves(wsg)):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                       rtol=2e-4, atol=1e-6)
        np.testing.assert_allclose(np.asarray(d_embed), np.asarray(wde),
                                   rtol=2e-4, atol=1e-6)
        np.testing.assert_allclose(np.asarray(d_head), np.asarray(wdh),
                                   rtol=2e-4, atol=1e-6)
