"""Resilience layer unit tier (apex_tpu.resilience): deterministic
fault injection, kill-safe manifest checkpoints, and the ResilientLoop
escalation ladder — plus the PrefetchLoader retry path and the atomic
``save_checkpoint`` regression.  The end-to-end kill-and-resume and
serving chaos soaks live in tests/test_chaos.py (``-m chaos``).
"""

import json
import os
import signal

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu import utils
from apex_tpu.resilience import (
    CheckpointCorrupt,
    DivergenceError,
    FaultPlan,
    FaultSpec,
    InjectedIOError,
    Preempted,
    ResilientCheckpointer,
    ResilientLoop,
    TransientStepError,
    WatchdogConfig,
    WatchdogTimeout,
    active,
    inject,
    install_plan,
    clear_plan,
    plan_from_env,
    verify_checkpoint,
)
from apex_tpu.utils.metrics import Counters, counters


class TestFaultPlan:
    def test_step_pinned_fires_once_per_matching_step(self):
        plan = FaultPlan([FaultSpec(site="s", kind="io", step=3)])
        with active(plan):
            for i in range(3):
                assert inject("s", step=i) == ()
            with pytest.raises(InjectedIOError):
                inject("s", step=3)
            assert inject("s", step=4) == ()

    def test_times_caps_total_firings(self):
        plan = FaultPlan([FaultSpec(site="s", kind="io", times=2)])
        with active(plan):
            for _ in range(2):
                with pytest.raises(InjectedIOError):
                    inject("s")
            assert inject("s") == ()        # budget spent

    def test_every_and_site_counter(self):
        # step=None uses the site's own call counter
        plan = FaultPlan([FaultSpec(site="s", kind="nan", every=3)])
        with active(plan):
            fired = [bool(inject("s")) for _ in range(7)]
        assert fired == [True, False, False, True, False, False, True]

    def test_prob_deterministic_across_replays(self):
        plan = FaultPlan([FaultSpec(site="s", kind="nan", prob=0.5)],
                         seed=7)

        def run():
            plan.reset()
            with active(plan):
                return [bool(inject("s", step=i)) for i in range(64)]

        first, second = run(), run()
        assert first == second
        assert 5 < sum(first) < 59      # actually probabilistic-ish

    def test_seed_changes_prob_pattern(self):
        def pattern(seed):
            plan = FaultPlan(
                [FaultSpec(site="s", kind="nan", prob=0.5)], seed=seed)
            with active(plan):
                return [bool(inject("s", step=i)) for i in range(64)]

        assert pattern(0) != pattern(1)

    def test_slow_sleeps_and_reports(self):
        import time

        plan = FaultPlan(
            [FaultSpec(site="s", kind="slow", step=0, delay=0.05)])
        with active(plan):
            t0 = time.monotonic()
            fired = inject("s", step=0)
            assert time.monotonic() - t0 >= 0.05
        assert [f.kind for f in fired] == ["slow"]

    def test_transient_carries_slots(self):
        plan = FaultPlan(
            [FaultSpec(site="s", kind="transient", slots=(1,))])
        with active(plan):
            with pytest.raises(TransientStepError) as ei:
                inject("s")
        assert ei.value.slots == (1,)

    def test_json_roundtrip(self):
        plan = FaultPlan(
            [FaultSpec(site="a", kind="io", step=5),
             FaultSpec(site="b", kind="slow", every=2, delay=0.5),
             FaultSpec(site="c", kind="transient", prob=0.25,
                       times=3, slots=(0, 2))],
            seed=11)
        plan2 = FaultPlan.parse(plan.to_json())
        assert plan2.seed == 11
        assert plan2.faults == plan.faults

    def test_env_entry_point(self, monkeypatch):
        spec = {"seed": 3,
                "faults": [{"site": "e", "kind": "io", "step": 0}]}
        monkeypatch.setenv("APEX_TPU_FAULT_PLAN", json.dumps(spec))
        clear_plan()                        # re-arm the env lookup
        try:
            with pytest.raises(InjectedIOError):
                inject("e", step=0)
        finally:
            install_plan(None)              # detach from env for peers

    def test_env_file_form(self, tmp_path, monkeypatch):
        p = tmp_path / "plan.json"
        p.write_text(json.dumps(
            {"faults": [{"site": "f", "kind": "nan", "step": 1}]}))
        monkeypatch.setenv("APEX_TPU_FAULT_PLAN", f"@{p}")
        plan = plan_from_env()
        assert plan.faults[0].site == "f"
        assert plan.faults[0].kind == "nan"

    def test_no_plan_is_a_cheap_noop(self):
        install_plan(None)
        try:
            assert inject("anything") == ()
        finally:
            clear_plan()

    def test_bad_kind_rejected(self):
        with pytest.raises(ValueError, match="kind"):
            FaultSpec(site="s", kind="explode")

    def test_preempt_without_handler_raises(self):
        # outside a ResilientLoop no SIGTERM handler is installed, so
        # the injected preemption must surface as Preempted (firing a
        # real SIG_DFL SIGTERM would kill the test runner)
        plan = FaultPlan([FaultSpec(site="s", kind="preempt")])
        prev = signal.signal(signal.SIGTERM, signal.SIG_DFL)
        try:
            with active(plan):
                with pytest.raises(Preempted):
                    inject("s")
        finally:
            signal.signal(signal.SIGTERM, prev)


class TestCounters:
    def test_inc_get_snapshot_reset(self):
        c = Counters()
        assert c.get("x") == 0
        assert c.inc("x") == 1
        assert c.inc("x", 4) == 5
        c.inc("y")
        assert c.snapshot() == {"x": 5, "y": 1}
        c.reset()
        assert c.get("x") == 0


class TestAtomicSaveCheckpoint:
    """Satellite regression: ``save_checkpoint(force=True)`` must stage
    and atomically swap — a fault mid-save can never destroy the
    previous checkpoint."""

    def test_io_fault_mid_force_save_preserves_old(self, tmp_path):
        tree = {"a": jnp.arange(4.0)}
        path = str(tmp_path / "ckpt")
        utils.save_checkpoint(path, tree)
        plan = FaultPlan(
            [FaultSpec(site="checkpoint.write", kind="io")])
        with active(plan):
            with pytest.raises(InjectedIOError):
                utils.save_checkpoint(
                    path, {"a": jnp.zeros(4)}, force=True)
        # the old checkpoint is fully intact, and no staging debris
        # shadows it
        restored = utils.restore_checkpoint(path, tree)
        np.testing.assert_array_equal(np.asarray(restored["a"]),
                                      np.arange(4.0))
        stale = [n for n in os.listdir(tmp_path)
                 if ".stage-" in n or ".prev-" in n]
        assert stale == [], stale

    def test_force_save_still_overwrites_cleanly(self, tmp_path):
        tree = {"a": jnp.arange(4.0)}
        path = str(tmp_path / "ckpt")
        utils.save_checkpoint(path, tree)
        utils.save_checkpoint(path, {"a": jnp.zeros(4)}, force=True)
        restored = utils.restore_checkpoint(path, tree)
        np.testing.assert_array_equal(np.asarray(restored["a"]),
                                      np.zeros(4))

    def test_failed_swap_rolls_old_checkpoint_back(self, tmp_path,
                                                   monkeypatch):
        """If the stage→path rename of an overwrite fails AFTER the old
        checkpoint was parked aside, cleanup must put the old one back
        at ``path`` — never delete the only complete copy and leave
        nothing restorable."""
        tree = {"a": jnp.arange(4.0)}
        path = str(tmp_path / "ckpt")
        utils.save_checkpoint(path, tree)
        real_rename = os.rename

        def flaky_rename(src, dst):
            if dst == path and ".stage-" in src:
                raise OSError("simulated swap failure")
            return real_rename(src, dst)

        monkeypatch.setattr(os, "rename", flaky_rename)
        with pytest.raises(OSError, match="simulated swap"):
            utils.save_checkpoint(path, {"a": jnp.zeros(4)},
                                  force=True)
        monkeypatch.setattr(os, "rename", real_rename)
        assert os.path.exists(path), "old checkpoint not rolled back"
        restored = utils.restore_checkpoint(path, tree)
        np.testing.assert_array_equal(np.asarray(restored["a"]),
                                      np.arange(4.0))


class TestResilientCheckpointer:
    def _tree(self, scale=1.0):
        return {"w": jnp.arange(6.0).reshape(2, 3) * scale,
                "step": jnp.asarray(int(scale), jnp.int32)}

    def test_roundtrip_with_manifest(self, tmp_path):
        ck = ResilientCheckpointer(str(tmp_path), keep=3)
        ck.save(10, self._tree())
        assert ck.all_steps() == [10]
        manifest = verify_checkpoint(
            os.path.join(str(tmp_path), "step_00000010"))
        assert manifest["step"] == 10
        assert manifest["files"]            # hashed payload exists
        step, tree = ck.restore_latest(self._tree())
        assert step == 10
        np.testing.assert_array_equal(np.asarray(tree["w"]),
                                      np.asarray(self._tree()["w"]))

    def _corrupt_one_payload_file(self, root):
        victims = []
        for base, _dirs, names in os.walk(root):
            for name in names:
                if "manifest" in name:
                    continue
                full = os.path.join(base, name)
                if os.path.getsize(full) > 0:
                    victims.append(full)
        assert victims, f"no payload files under {root}"
        victim = sorted(victims)[0]
        with open(victim, "r+b") as f:
            blob = f.read(16)
            f.seek(0)
            f.write(bytes(b ^ 0xFF for b in blob))
        return victim

    def test_corrupt_latest_skipped_for_previous(self, tmp_path):
        ck = ResilientCheckpointer(str(tmp_path), keep=3)
        ck.save(1, self._tree(1.0))
        ck.save(2, self._tree(2.0))
        self._corrupt_one_payload_file(
            os.path.join(str(tmp_path), "step_00000002"))
        before = counters.get("checkpoint.corrupt_skipped")
        step, tree = ck.restore_latest(self._tree())
        assert step == 1
        np.testing.assert_array_equal(
            np.asarray(tree["w"]), np.asarray(self._tree(1.0)["w"]))
        assert counters.get("checkpoint.corrupt_skipped") > before

    def test_partial_checkpoint_without_manifest_skipped(self, tmp_path):
        ck = ResilientCheckpointer(str(tmp_path), keep=3)
        ck.save(1, self._tree(1.0))
        ck.save(2, self._tree(2.0))
        os.remove(os.path.join(str(tmp_path), "step_00000002",
                               "manifest.json"))
        step, _tree = ck.restore_latest(self._tree())
        assert step == 1

    def test_verify_raises_on_tamper(self, tmp_path):
        ck = ResilientCheckpointer(str(tmp_path))
        ck.save(5, self._tree())
        root = os.path.join(str(tmp_path), "step_00000005")
        self._corrupt_one_payload_file(root)
        with pytest.raises(CheckpointCorrupt, match="hash mismatch"):
            verify_checkpoint(root)

    def test_rolling_gc_keeps_n(self, tmp_path):
        ck = ResilientCheckpointer(str(tmp_path), keep=2)
        for step in (1, 2, 3, 4):
            ck.save(step, self._tree(float(step)))
        assert ck.all_steps() == [3, 4]

    def test_io_fault_mid_save_leaves_committed_intact(self, tmp_path):
        ck = ResilientCheckpointer(str(tmp_path), keep=3)
        ck.save(1, self._tree(1.0))
        plan = FaultPlan(
            [FaultSpec(site="checkpoint.save", kind="io")])
        with active(plan):
            with pytest.raises(InjectedIOError):
                ck.save(2, self._tree(2.0))
        assert ck.all_steps() == [1]
        step, _ = ck.restore_latest(self._tree())
        assert step == 1
        # no staging debris left behind
        assert [n for n in os.listdir(str(tmp_path))
                if n.startswith(".stage-")] == []

    def test_async_save_and_error_surfacing(self, tmp_path):
        ck = ResilientCheckpointer(str(tmp_path), keep=3)
        ck.save(1, self._tree(1.0), blocking=False)
        ck.wait()
        assert ck.all_steps() == [1]
        plan = FaultPlan(
            [FaultSpec(site="checkpoint.save", kind="io")])
        with active(plan):
            ck.save(2, self._tree(2.0), blocking=False)
            ck.wait()
        # the async failure surfaces on the NEXT save call
        with pytest.raises(InjectedIOError):
            ck.save(3, self._tree(3.0))
        assert ck.all_steps() == [1]

    def test_empty_directory_restores_none(self, tmp_path):
        ck = ResilientCheckpointer(str(tmp_path))
        assert ck.restore_latest(self._tree()) is None


def _linear_step(carry, batch):
    carry = jax.tree.map(lambda x: x + batch, carry)
    finite = bool(np.isfinite(float(jax.tree.leaves(carry)[0][0])))
    return carry, {"loss": float(batch), "finite": finite}


class TestResilientLoop:
    def test_plain_run_matches_bare_loop(self):
        loop = ResilientLoop(_linear_step)
        carry, report = loop.run({"w": jnp.zeros(2)},
                                 lambda s: np.float32(1.0), 10)
        assert float(carry["w"][0]) == 10.0
        assert report.steps_run == 10 and not report.preempted

    def test_injected_preemption_checkpoints_and_resumes(self, tmp_path):
        ck = ResilientCheckpointer(str(tmp_path), keep=3)
        loop = ResilientLoop(_linear_step, checkpointer=ck,
                             checkpoint_every=4)
        plan = FaultPlan([FaultSpec(site="train.step", kind="preempt",
                                    step=6, times=1)])
        with active(plan):
            carry, report = loop.run({"w": jnp.zeros(2)},
                                     lambda s: np.float32(1.0), 20)
        assert report.preempted and report.final_step == 6
        assert float(carry["w"][0]) == 6.0
        assert ck.latest_step() == 6        # the preemption checkpoint
        # relaunch: auto-resume from 6, finish to 20
        carry, report2 = loop.run({"w": jnp.zeros(2)},
                                  lambda s: np.float32(1.0), 20)
        assert report2.resumed_from == 6
        assert report2.steps_run == 14
        assert float(carry["w"][0]) == 20.0

    def test_programmatic_preemption(self, tmp_path):
        ck = ResilientCheckpointer(str(tmp_path))
        loop = ResilientLoop(_linear_step, checkpointer=ck,
                             checkpoint_every=100)

        calls = {"n": 0}

        def data_fn(step):
            calls["n"] += 1
            if calls["n"] == 3:
                loop.request_preemption()
            return np.float32(1.0)

        carry, report = loop.run({"w": jnp.zeros(2)}, data_fn, 50)
        assert report.preempted
        assert 3 <= report.final_step <= 4
        assert ck.latest_step() == report.final_step

    def test_nan_escalation_rewinds_to_checkpoint(self, tmp_path):
        """The ladder's rung 2: a transient NaN burst (injected once)
        trips the sentinel, the loop rewinds to the last good
        checkpoint and completes with finite state."""
        ck = ResilientCheckpointer(str(tmp_path), keep=3)
        loop = ResilientLoop(_linear_step, checkpointer=ck,
                             checkpoint_every=5,
                             finite_of=lambda aux: aux["finite"],
                             nan_tolerance=2, max_rewinds=2)
        plan = FaultPlan([FaultSpec(site="train.compute", kind="nan",
                                    step=7, times=1)])
        with active(plan):
            carry, report = loop.run({"w": jnp.zeros(2)},
                                     lambda s: np.float32(1.0), 12)
        assert report.rewinds == 1
        assert report.nonfinite_steps >= 2
        assert np.all(np.isfinite(np.asarray(carry["w"])))
        # rewound to step 5, replayed 5..12 clean (fault spent)
        assert float(carry["w"][0]) == 12.0

    def test_divergence_abort_with_diagnostics(self):
        # no checkpointer -> no rewind rung -> abort with a report
        loop = ResilientLoop(_linear_step,
                             finite_of=lambda aux: aux["finite"],
                             nan_tolerance=2, max_rewinds=1)
        plan = FaultPlan([FaultSpec(site="train.compute", kind="nan")])
        with active(plan):
            with pytest.raises(DivergenceError) as ei:
                loop.run({"w": jnp.zeros(2)},
                         lambda s: np.float32(1.0), 10)
        report = ei.value.report
        assert report.diagnostics["nan_tolerance"] == 2
        assert report.nonfinite_steps >= 2
        assert "counters" in report.diagnostics

    def test_rewind_budget_exhausted_aborts(self, tmp_path):
        # the fault re-fires forever -> every rewind replays into the
        # same NaN -> the ladder must abort, not loop
        ck = ResilientCheckpointer(str(tmp_path), keep=2)
        loop = ResilientLoop(_linear_step, checkpointer=ck,
                             checkpoint_every=2,
                             finite_of=lambda aux: aux["finite"],
                             nan_tolerance=1, max_rewinds=2)
        plan = FaultPlan([FaultSpec(site="train.compute", kind="nan",
                                    steps=tuple(range(3, 100)))])
        with active(plan):
            with pytest.raises(DivergenceError) as ei:
                loop.run({"w": jnp.zeros(2)},
                         lambda s: np.float32(1.0), 20)
        assert ei.value.report.rewinds == 3     # 2 spent + the fatal one

    def test_watchdog_dumps_and_raises(self, tmp_path):
        dump = str(tmp_path / "watchdog.txt")
        loop = ResilientLoop(
            _linear_step,
            watchdog=WatchdogConfig(min_deadline=0.2,
                                    deadline_factor=50.0,
                                    warmup_steps=1, poll=0.02,
                                    dump_path=dump))
        plan = FaultPlan([FaultSpec(site="train.compute", kind="slow",
                                    step=3, delay=0.8)])
        with active(plan):
            with pytest.raises(WatchdogTimeout):
                loop.run({"w": jnp.zeros(2)},
                         lambda s: np.float32(1.0), 10)
        blob = open(dump).read()
        assert "live thread stacks" in blob
        assert "device / mesh state" in blob
        assert "MainThread" in blob

    def test_watchdog_quiet_on_healthy_steps(self):
        loop = ResilientLoop(
            _linear_step,
            watchdog=WatchdogConfig(min_deadline=30.0, poll=0.02))
        carry, report = loop.run({"w": jnp.zeros(2)},
                                 lambda s: np.float32(1.0), 8)
        assert not report.watchdog_fired
        assert float(carry["w"][0]) == 8.0

    def test_loss_scale_diag_in_divergence_report(self):
        """The diagnostic includes the loss-scaler state when the carry
        is a MixedPrecisionTrainState — the backoff_exhausted hand-off
        from DynamicLossScale's own state machine."""
        import optax

        from apex_tpu import amp

        params = {"w": jnp.ones((2, 2))}
        state = amp.initialize(
            lambda p, x: x @ p["w"], params, optax.sgd(1e-2),
            opt_level="O2", half_dtype=jnp.bfloat16)

        def step(carry, batch):
            def loss_fn(p):
                return carry.scale_loss(
                    jnp.sum(carry.apply_fn(p, batch) ** 2))
            grads = jax.grad(loss_fn)(carry.compute_params())
            new_state, finite = carry.apply_gradients(grads=grads)
            return new_state, {"finite": finite}

        loop = ResilientLoop(step,
                             finite_of=lambda aux: aux["finite"],
                             nan_tolerance=1, max_rewinds=0)
        plan = FaultPlan([FaultSpec(site="train.compute", kind="nan")])
        with active(plan):
            with pytest.raises(DivergenceError) as ei:
                loop.run(state, lambda s: jnp.ones((1, 2)), 5)
        diag = ei.value.report.diagnostics
        assert "loss_scale" in diag
        assert "loss_scale_backoff_exhausted" in diag


class TestBackoffExhausted:
    def test_flags_only_at_min_scale(self):
        from apex_tpu.core.loss_scale import DynamicLossScale

        ls = DynamicLossScale(init_scale=4.0, min_scale=1.0)
        state = ls.init()
        assert not bool(ls.backoff_exhausted(state))
        for _ in range(3):      # 4 -> 2 -> 1 (clamped)
            state = ls.adjust(state, jnp.asarray(False))
        assert float(state.loss_scale) == 1.0
        assert bool(ls.backoff_exhausted(state))


class _FlakySource:
    """__next__ raises OSError on chosen calls; safe to re-pull."""

    def __init__(self, n=4, fail_calls=()):
        self.n = n
        self.fail_calls = set(fail_calls)
        self.calls = 0
        self.emitted = 0

    def __iter__(self):
        return self

    def __next__(self):
        self.calls += 1
        if self.calls in self.fail_calls:
            raise OSError(f"flaky read #{self.calls}")
        if self.emitted >= self.n:
            raise StopIteration
        self.emitted += 1
        return np.full((2,), float(self.emitted), np.float32)


class TestPrefetchRetry:
    def test_retries_absorb_transient_failures(self):
        from apex_tpu.data.prefetch import PrefetchLoader

        before = counters.get("data.retry")
        src = _FlakySource(n=4, fail_calls={2, 5})
        out = [float(np.asarray(b)[0])
               for b in PrefetchLoader(src, retries=2,
                                       retry_backoff=0.01)]
        assert out == [1.0, 2.0, 3.0, 4.0]
        assert counters.get("data.retry") - before == 2

    def test_exhausted_retries_surface_in_consumer(self):
        from apex_tpu.data.prefetch import PrefetchLoader

        src = _FlakySource(n=4, fail_calls={2, 3, 4, 5})
        loader = PrefetchLoader(src, retries=2, retry_backoff=0.01)
        with pytest.raises(OSError, match="flaky read"):
            list(loader)

    def test_zero_retries_is_the_old_behavior(self):
        from apex_tpu.data.prefetch import PrefetchLoader

        src = _FlakySource(n=4, fail_calls={2})
        with pytest.raises(OSError):
            list(PrefetchLoader(src))

    def test_injected_data_fault_is_retried(self):
        from apex_tpu.data.prefetch import PrefetchLoader

        plan = FaultPlan([FaultSpec(site="data.next", kind="io",
                                    step=1, times=1)])
        src = _FlakySource(n=3)
        with active(plan):
            out = [float(np.asarray(b)[0])
                   for b in PrefetchLoader(src, retries=1,
                                           retry_backoff=0.01)]
        assert out == [1.0, 2.0, 3.0]
