"""Flash-attention kernel golden tests (vs eager composition and torch
SDPA) — fwd and bwd, causal/rectangular/GQA, reference pattern of
``apex/contrib/test/multihead_attn`` (SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from apex_tpu import ops
from apex_tpu.ops.attention import fused_attention, attention_reference

# L0 fast tier: golden kernel/state-machine tests (pytest -m l0)
pytestmark = pytest.mark.l0

D = 128


@pytest.fixture(autouse=True)
def _true_fp32_matmuls():
    """Pin fp32 matmuls: on TPU, DEFAULT precision runs the *reference
    composition* in bf16 MXU passes (~1e-2 error), which would fail the
    kernel-vs-golden tolerances for hardware reasons, not math."""
    with jax.default_matmul_precision("highest"):
        yield


def _qkv(rng, b=2, sq=256, sk=256, h=2, hk=None, dtype=jnp.float32):
    hk = hk or h
    q = jnp.asarray(rng.normal(size=(b, sq, h, D)), dtype)
    k = jnp.asarray(rng.normal(size=(b, sk, hk, D)), dtype)
    v = jnp.asarray(rng.normal(size=(b, sk, hk, D)), dtype)
    return q, k, v


class TestForward:
    @pytest.mark.parametrize("causal", [False, True])
    def test_vs_reference(self, rng, causal):
        q, k, v = _qkv(rng)
        got = fused_attention(q, k, v, causal=causal,
                              implementation="pallas_interpret")
        want = attention_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-4)

    def test_vs_torch_sdpa(self, rng):
        q, k, v = _qkv(rng, b=1, sq=128, sk=128, h=2)
        got = fused_attention(q, k, v, causal=True,
                              implementation="pallas_interpret")
        # torch sdpa wants (b, h, s, d)
        tq, tk, tv = [torch.tensor(np.asarray(t)).permute(0, 2, 1, 3)
                      for t in (q, k, v)]
        want = torch.nn.functional.scaled_dot_product_attention(
            tq, tk, tv, is_causal=True).permute(0, 2, 1, 3)
        np.testing.assert_allclose(np.asarray(got), want.numpy(),
                                   rtol=1e-4, atol=1e-4)

    def test_rectangular_causal(self, rng):
        # decode-style: sq < sk with causal offset
        q, k, v = _qkv(rng, sq=128, sk=384)
        got = fused_attention(q, k, v, causal=True,
                              implementation="pallas_interpret")
        want = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-4)

    def test_gqa(self, rng):
        q, k, v = _qkv(rng, h=4, hk=2)
        got = fused_attention(q, k, v,
                              implementation="pallas_interpret")
        want = attention_reference(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-4)

    def test_long_query_causal(self, rng):
        # sq > sk causal: leading query rows see no key at all; both
        # paths must agree (zeros for fully-masked rows)
        q, k, v = _qkv(rng, sq=256, sk=128)
        got = fused_attention(q, k, v, causal=True,
                              implementation="pallas_interpret")
        want = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-4)
        # the first sq - sk rows are fully masked -> exact zeros
        np.testing.assert_array_equal(np.asarray(got)[:, :128], 0.0)

    def test_bf16(self, rng):
        q, k, v = _qkv(rng, dtype=jnp.bfloat16)
        got = fused_attention(q, k, v, causal=True,
                              implementation="pallas_interpret")
        want = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   np.asarray(want, np.float32),
                                   rtol=5e-2, atol=5e-2)

    def test_custom_scale(self, rng):
        q, k, v = _qkv(rng, sq=128, sk=128)
        got = fused_attention(q, k, v, scale=0.25,
                              implementation="pallas_interpret")
        want = attention_reference(q, k, v, scale=0.25)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-4)

    @pytest.mark.parametrize("shape", [
        (1, 2, 1, 128),    # per-head row (ALiBi)
        (2, 1, 1, 128),    # per-batch key row (padding)
        (1, 1, 128, 128),  # shared score bias
        (1, 2, 128, 128),  # per-head relative-position
        (2, 2, 128, 128),  # full
    ])
    def test_bias_tiles_ride_pallas(self, rng, shape):
        """Every broadcastable (b|1, h|1, sq|1, sk) bias rides the
        kernel (round-1 verdict item 6: ALiBi / relative-position must
        not silently fall back to the O(S^2) composition)."""
        q, k, v = _qkv(rng, sq=128, sk=128)
        bias = jnp.asarray(rng.normal(size=shape), jnp.float32)
        got = fused_attention(q, k, v, bias=bias,
                              implementation="pallas_interpret")
        want = attention_reference(q, k, v, bias=bias)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    def test_per_head_bias_grads(self, rng):
        q, k, v = _qkv(rng, sq=128, sk=128)
        bias = jnp.asarray(rng.normal(size=(1, 2, 128, 128)), jnp.float32)

        def f(impl):
            def loss(q, k, v):
                o = fused_attention(q, k, v, bias=bias, causal=True,
                                    implementation=impl)
                return jnp.sum(o * o)
            return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

        # On real TPU both sides' f32 matmuls run default-precision
        # MXU passes (~4e-3 relative each, in different directions);
        # kernel ≡ interpret stays 2e-7 there (round-5 on-chip run),
        # so the loose tier checks implementations, not MXU rounding.
        tol = (1e-4 if jax.default_backend() == "cpu" else 3e-2)
        for g, w, name in zip(f("pallas_interpret"), f("xla"), "qkv"):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                       rtol=tol, atol=tol,
                                       err_msg=name)

    def test_learned_bias_requires_grad_routes_to_xla(self, rng):
        """A learned bias needs its gradient — bias_requires_grad=True
        must use the differentiable composition (and actually produce a
        non-zero bias cotangent)."""
        q, k, v = _qkv(rng, sq=128, sk=128)
        bias = jnp.asarray(rng.normal(size=(1, 2, 128, 128)) * 0.1,
                           jnp.float32)

        def loss(bias):
            o = fused_attention(q, k, v, bias=bias,
                                bias_requires_grad=True,
                                implementation="auto")
            return jnp.sum(o * o)

        db = jax.grad(loss)(bias)
        assert float(jnp.abs(db).max()) > 0.0

    def test_3d_bias_falls_back_to_xla(self, rng):
        q, k, v = _qkv(rng, sq=128, sk=128)
        bias = jnp.asarray(rng.normal(size=(2, 128, 128)), jnp.float32)
        got = fused_attention(q, k, v, bias=bias[:, None],
                              implementation="auto")
        want = attention_reference(q, k, v, bias=bias[:, None])
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_key_padding_bias_pallas(self, rng, causal):
        # (b, 1, 1, sk) key-padding bias rides the Pallas kernel
        from apex_tpu.ops.attention import mask_to_bias
        q, k, v = _qkv(rng)
        masked = jnp.zeros((2, 256), bool).at[:, 200:].set(True)
        bias = mask_to_bias(masked)[:, None, None, :]
        got = fused_attention(q, k, v, causal=causal, bias=bias,
                              implementation="pallas_interpret")
        want = attention_reference(q, k, v, causal=causal, bias=bias)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-4)

    def test_key_padding_bias_grads(self, rng):
        from apex_tpu.ops.attention import mask_to_bias
        q, k, v = _qkv(rng, b=1, sq=128, sk=128, h=2)
        masked = jnp.zeros((1, 128), bool).at[:, 100:].set(True)
        bias = mask_to_bias(masked)[:, None, None, :]

        def f(impl):
            def loss(q, k, v):
                o = fused_attention(q, k, v, bias=bias,
                                    implementation=impl)
                return jnp.sum(jnp.tanh(o))
            return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

        for gf, gr, name in zip(f("pallas_interpret"), f("xla"), "qkv"):
            np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                       rtol=1e-3, atol=1e-3,
                                       err_msg=f"d{name} mismatch")

    def test_all_keys_padded_rows_zero(self, rng):
        from apex_tpu.ops.attention import mask_to_bias
        q, k, v = _qkv(rng, b=1, sq=128, sk=128, h=1)
        masked = jnp.ones((1, 128), bool)          # everything padded
        bias = mask_to_bias(masked)[:, None, None, :]
        got = fused_attention(q, k, v, bias=bias,
                              implementation="pallas_interpret")
        np.testing.assert_array_equal(np.asarray(got), 0.0)
        want = attention_reference(q, k, v, bias=bias)
        np.testing.assert_array_equal(np.asarray(want), 0.0)


@pytest.mark.slow
class TestBandEnumeration:
    """[slow: exhaustive all-(nb, W) enumeration ≈ 40s on CPU]
    Exhaustive validation of the closed-form banded grid math that
    every causal kernel's BlockSpec index maps and init/final
    predicates run on (W = nb-1 is the full causal triangle)."""

    def test_band_maps_exact_all_nb_all_w(self):
        import jax.numpy as jnp

        from apex_tpu.ops.attention import (
            _band_ij,
            _band_ji,
            _band_tiles,
            _tri_ij,
            _tri_ji,
        )

        for nb in (1, 2, 3, 5, 8, 13):
            for W in range(nb):
                exp_ij = [(i, j) for i in range(nb)
                          for j in range(max(0, i - W), i + 1)]
                n = _band_tiles(nb, W)
                assert n == len(exp_ij), (nb, W)
                ts = jnp.arange(n)
                i, j = _band_ij(ts, W)
                got = list(zip(np.asarray(i).tolist(),
                               np.asarray(j).tolist()))
                assert got == exp_ij, (nb, W, got[:8])
                exp_ji = [(i, j) for j in range(nb)
                          for i in range(j, min(j + W, nb - 1) + 1)]
                i2, j2 = _band_ji(ts, W, nb)
                got2 = list(zip(np.asarray(i2).tolist(),
                                np.asarray(j2).tolist()))
                assert got2 == exp_ji, (nb, W, got2[:8])
                if W == nb - 1:       # degenerates to the triangle
                    ti, tj = _tri_ij(ts)
                    assert got == list(zip(
                        np.asarray(ti).tolist(), np.asarray(tj).tolist()))
                    ti2, tj2 = _tri_ji(ts, nb)
                    assert got2 == list(zip(
                        np.asarray(ti2).tolist(),
                        np.asarray(tj2).tolist()))

    def test_band_w_block_conversion(self):
        from apex_tpu.ops.attention import _band_w

        # W = smallest block count whose oldest tile still reaches the
        # window start: exact formula cross-check on small cases
        for bk in (2, 4, 64):
            for nb in (2, 4, 8):
                for w in range(1, nb * bk + 1):
                    W = _band_w(w, True, nb, bk)
                    exact = min(nb - 1, (w + bk - 2) // bk)
                    assert W == exact, (bk, nb, w, W, exact)
                    # tile (i, i-W) must contain a visible key for the
                    # block's queries; tile (i, i-W-1) must not (when
                    # it exists): verified at i = nb-1
                    i = nb - 1
                    q_first = i * bk
                    if i - W >= 1:
                        dead_last = (i - W) * bk - 1
                        assert dead_last < q_first - w + 1


class TestSlidingWindow:
    """Banded-grid sliding-window attention (beyond-reference: the
    reference's fmha has no windowing).  The band enumeration is
    validated exactly in-kernel here by comparing against the masked
    eager composition, fwd and bwd, across window widths that land
    inside / across / beyond block boundaries."""

    # sq=256 with 64-blocks -> nb=4: windows hit W=0,1,2 and the
    # degenerate full-triangle case
    @pytest.mark.parametrize("window", [1, 33, 64, 65, 128, 200, 256])
    def test_fwd_vs_reference(self, rng, window):
        q, k, v = _qkv(rng)
        got = fused_attention(q, k, v, causal=True, window=window,
                              block_q=64, block_k=64,
                              implementation="pallas_interpret")
        want = attention_reference(q, k, v, causal=True, window=window)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-4)

    @pytest.mark.parametrize("window", [33, 65, 128])
    def test_grads_vs_reference(self, rng, window):
        q, k, v = _qkv(rng, b=1)

        def loss(fn):
            def f(q, k, v):
                o = fn(q, k, v)
                return jnp.sum(jnp.tanh(o))
            return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

        g_fused = loss(lambda q, k, v: fused_attention(
            q, k, v, causal=True, window=window, block_q=64,
            block_k=64, implementation="pallas_interpret"))
        g_ref = loss(lambda q, k, v: attention_reference(
            q, k, v, causal=True, window=window))
        for gf, gr, name in zip(g_fused, g_ref, "qkv"):
            np.testing.assert_allclose(
                np.asarray(gf), np.asarray(gr), rtol=1e-3, atol=1e-3,
                err_msg=f"d{name} mismatch (window={window})")

    def test_gqa_window(self, rng):
        q, k, v = _qkv(rng, h=4, hk=2)
        got = fused_attention(q, k, v, causal=True, window=70,
                              block_q=64, block_k=64,
                              implementation="pallas_interpret")
        want = attention_reference(q, k, v, causal=True, window=70)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-4)

    def test_rectangular_window(self, rng):
        # sq < sk rides the rectangular grid with the window block skip
        q, k, v = _qkv(rng, sq=128, sk=384)
        got = fused_attention(q, k, v, causal=True, window=100,
                              block_q=64, block_k=64,
                              implementation="pallas_interpret")
        want = attention_reference(q, k, v, causal=True, window=100)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-4)

    def test_window_with_bias(self, rng):
        q, k, v = _qkv(rng)
        bias = jnp.asarray(
            rng.normal(size=(1, 2, 1, 256)), jnp.float32)
        got = fused_attention(q, k, v, causal=True, window=96,
                              bias=bias, block_q=64, block_k=64,
                              implementation="pallas_interpret")
        want = attention_reference(q, k, v, causal=True, window=96,
                                   bias=bias)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-4)

    def test_window_with_dropout_grads(self, rng):
        q, k, v = _qkv(rng, b=1)

        def loss(impl_fn):
            def f(q, k, v):
                return jnp.sum(jnp.tanh(impl_fn(q, k, v)))
            return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

        g_fused = loss(lambda q, k, v: fused_attention(
            q, k, v, causal=True, window=96, dropout_rate=0.3,
            dropout_rng=1234, block_q=64, block_k=64,
            implementation="pallas_interpret"))
        g_ref = loss(lambda q, k, v: attention_reference(
            q, k, v, causal=True, window=96, dropout_rate=0.3,
            dropout_seed=1234))
        for gf, gr, name in zip(g_fused, g_ref, "qkv"):
            np.testing.assert_allclose(
                np.asarray(gf), np.asarray(gr), rtol=1e-3, atol=1e-3,
                err_msg=f"d{name} mismatch")

    def test_full_window_is_noop(self, rng):
        q, k, v = _qkv(rng)
        got = fused_attention(q, k, v, causal=True, window=256,
                              block_q=64, block_k=64,
                              implementation="pallas_interpret")
        want = fused_attention(q, k, v, causal=True,
                               block_q=64, block_k=64,
                               implementation="pallas_interpret")
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-6, atol=1e-6)

    def test_window_requires_causal(self, rng):
        q, k, v = _qkv(rng, sq=64, sk=64)
        with pytest.raises(ValueError, match="causal"):
            fused_attention(q, k, v, causal=False, window=32)

    def test_bad_window_raises(self, rng):
        q, k, v = _qkv(rng, sq=64, sk=64)
        with pytest.raises(ValueError, match="window"):
            fused_attention(q, k, v, causal=True, window=0)


class TestBackward:
    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_vs_reference(self, rng, causal):
        q, k, v = _qkv(rng, b=1, sq=256, sk=256, h=2)

        def f_fused(q, k, v):
            o = fused_attention(q, k, v, causal=causal,
                                implementation="pallas_interpret")
            return jnp.sum(o * o)

        def f_ref(q, k, v):
            o = attention_reference(q, k, v, causal=causal)
            return jnp.sum(o * o)

        g_fused = jax.grad(f_fused, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for gf, gr, name in zip(g_fused, g_ref, "qkv"):
            np.testing.assert_allclose(
                np.asarray(gf), np.asarray(gr), rtol=1e-3, atol=1e-3,
                err_msg=f"d{name} mismatch")

    def test_grads_vs_torch(self, rng):
        b, s, h = 1, 128, 2
        q_np = rng.normal(size=(b, s, h, D)).astype(np.float32)
        k_np = rng.normal(size=(b, s, h, D)).astype(np.float32)
        v_np = rng.normal(size=(b, s, h, D)).astype(np.float32)

        def f(q, k, v):
            o = fused_attention(q, k, v, causal=True,
                                implementation="pallas_interpret")
            return jnp.sum(o)

        dq, dk, dv = jax.grad(f, argnums=(0, 1, 2))(
            jnp.asarray(q_np), jnp.asarray(k_np), jnp.asarray(v_np))

        tq, tk, tv = [torch.tensor(t, requires_grad=True)
                      for t in (q_np, k_np, v_np)]
        o = torch.nn.functional.scaled_dot_product_attention(
            tq.permute(0, 2, 1, 3), tk.permute(0, 2, 1, 3),
            tv.permute(0, 2, 1, 3), is_causal=True)
        o.sum().backward()
        np.testing.assert_allclose(np.asarray(dq), tq.grad.numpy(),
                                   rtol=1e-3, atol=1e-3)
        np.testing.assert_allclose(np.asarray(dk), tk.grad.numpy(),
                                   rtol=1e-3, atol=1e-3)
        np.testing.assert_allclose(np.asarray(dv), tv.grad.numpy(),
                                   rtol=1e-3, atol=1e-3)

    def test_gqa_grads(self, rng):
        q, k, v = _qkv(rng, b=1, sq=128, sk=128, h=4, hk=2)

        def f(impl):
            def loss(q, k, v):
                o = fused_attention(q, k, v, implementation=impl)
                return jnp.sum(jnp.tanh(o))
            return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

        for gf, gr, name in zip(f("pallas_interpret"), f("xla"), "qkv"):
            np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                       rtol=1e-3, atol=1e-3,
                                       err_msg=f"d{name} mismatch")

    def test_long_query_causal_grads(self, rng):
        q, k, v = _qkv(rng, b=1, sq=256, sk=128, h=1)

        def f(impl):
            def loss(q, k, v):
                o = fused_attention(q, k, v, causal=True,
                                    implementation=impl)
                return jnp.sum(jnp.tanh(o))
            return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

        for gf, gr, name in zip(f("pallas_interpret"), f("xla"), "qkv"):
            np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                       rtol=1e-3, atol=1e-3,
                                       err_msg=f"d{name} mismatch")

    def test_rectangular_grads(self, rng):
        q, k, v = _qkv(rng, b=1, sq=128, sk=256, h=1)

        def f(impl):
            def loss(q, k, v):
                o = fused_attention(q, k, v, causal=True,
                                    implementation=impl)
                return jnp.sum(jnp.tanh(o))
            return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

        for gf, gr in zip(f("pallas_interpret"), f("xla")):
            np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                       rtol=1e-3, atol=1e-3)


class TestDropout:
    """In-kernel attention-prob dropout (round-1 verdict item 6:
    reference multihead_attn kernels drop softmax probabilities with
    RNG replay in backward).  The counter-hash mask is regenerated
    bit-identically by the kernels and the jnp composition, so these
    are exact golden tests, not statistical ones."""

    def test_fwd_matches_reference_same_seed(self, rng):
        q, k, v = _qkv(rng)
        got = fused_attention(q, k, v, dropout_rate=0.2,
                              dropout_rng=1234,
                              implementation="pallas_interpret")
        want = attention_reference(q, k, v, dropout_rate=0.2,
                                   dropout_seed=1234)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_rate_zero_is_identity(self, rng):
        q, k, v = _qkv(rng, sq=128, sk=128)
        a = fused_attention(q, k, v, dropout_rate=0.0,
                            implementation="pallas_interpret")
        b = fused_attention(q, k, v, implementation="pallas_interpret")
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_reference(self, rng, causal):
        """RNG replay in backward: the dq and dkv kernels regenerate
        the forward's exact mask."""
        q, k, v = _qkv(rng, sq=128, sk=128)

        def f(impl):
            def loss(q, k, v):
                if impl == "xla":
                    o = attention_reference(q, k, v, causal=causal,
                                            dropout_rate=0.3,
                                            dropout_seed=77)
                else:
                    o = fused_attention(q, k, v, causal=causal,
                                        dropout_rate=0.3,
                                        dropout_rng=77,
                                        implementation=impl)
                return jnp.sum(o * o)
            return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

        for g, w, name in zip(f("pallas_interpret"), f("xla"), "qkv"):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                       rtol=5e-4, atol=5e-4,
                                       err_msg=name)

    def test_gqa_bias_dropout_combined(self, rng):
        q, k, v = _qkv(rng, sq=128, sk=128, h=4, hk=2)
        bias = jnp.asarray(rng.normal(size=(1, 4, 1, 128)), jnp.float32)

        def f(impl):
            def loss(q, k, v):
                if impl == "xla":
                    o = attention_reference(q, k, v, bias=bias,
                                            dropout_rate=0.1,
                                            dropout_seed=5)
                else:
                    o = fused_attention(q, k, v, bias=bias,
                                        dropout_rate=0.1, dropout_rng=5,
                                        implementation=impl)
                return jnp.sum(o * o)
            return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

        for g, w in zip(f("pallas_interpret"), f("xla")):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                       rtol=5e-4, atol=5e-4)

    def test_keep_fraction_and_seed_sensitivity(self, rng):
        from apex_tpu.ops.attention import dropout_keep_mask
        m = dropout_keep_mask(42, 4, 8, 128, 128, 0.3)
        frac = float(jnp.mean(m.astype(jnp.float32)))
        assert abs(frac - 0.7) < 0.01, frac
        m2 = dropout_keep_mask(43, 4, 8, 128, 128, 0.3)
        assert not bool(jnp.array_equal(m, m2))

    def test_mlm_seed_from_prng_key(self, rng):
        q, k, v = _qkv(rng, sq=128, sk=128)
        o = fused_attention(q, k, v, dropout_rate=0.2,
                            dropout_rng=jax.random.PRNGKey(3),
                            implementation="pallas_interpret")
        assert o.shape == q.shape
        assert bool(jnp.all(jnp.isfinite(o.astype(jnp.float32))))


class TestMultiheadAttnModules:
    def test_self_mha_shapes_and_grad(self, rng):
        import flax.linen as nn  # noqa: F401
        from apex_tpu.ops import SelfMultiheadAttn
        m = SelfMultiheadAttn(embed_dim=256, num_heads=2, causal=True,
                              include_norm_add=True, bias=True)
        x = jnp.asarray(rng.normal(size=(2, 128, 256)), jnp.float32)
        params = m.init(jax.random.PRNGKey(0), x)
        y = m.apply(params, x)
        assert y.shape == x.shape
        g = jax.grad(lambda p: jnp.sum(m.apply(p, x) ** 2))(params)
        leaves = jax.tree.leaves(g)
        assert all(bool(jnp.all(jnp.isfinite(l))) for l in leaves)
        assert any(float(jnp.max(jnp.abs(l))) > 0 for l in leaves)

    def test_boolean_padding_mask_excludes_keys(self, rng):
        # bool mask, True = masked (reference convention): masked keys
        # must get ~zero attention, not a +1.0 additive bias
        from apex_tpu.ops import SelfMultiheadAttn
        m = SelfMultiheadAttn(embed_dim=256, num_heads=2)
        x = jnp.asarray(rng.normal(size=(2, 16, 256)), jnp.float32)
        params = m.init(jax.random.PRNGKey(0), x)
        mask = jnp.zeros((2, 16), bool).at[:, 8:].set(True)
        y_masked = m.apply(params, x, key_padding_mask=mask)
        # output must equal attention over the first 8 tokens only
        y_trunc = m.apply(params, x[:, :8])
        np.testing.assert_allclose(np.asarray(y_masked[:, :8]),
                                   np.asarray(y_trunc),
                                   rtol=1e-5, atol=1e-5)

    def test_encdec_mha(self, rng):
        from apex_tpu.ops import EncdecMultiheadAttn
        m = EncdecMultiheadAttn(embed_dim=256, num_heads=2)
        q = jnp.asarray(rng.normal(size=(2, 64, 256)), jnp.float32)
        kv = jnp.asarray(rng.normal(size=(2, 128, 256)), jnp.float32)
        params = m.init(jax.random.PRNGKey(0), q, kv)
        y = m.apply(params, q, kv)
        assert y.shape == q.shape
