"""ZeRO-1/2 sharded optimizer states (ISSUE 11) — fast unit tier.

The claims under test, on the 8-virtual-device CPU mesh:

- **layout**: :func:`zero_partition` / :func:`zero_unpartition`
  roundtrip every leaf shape (odd sizes, scalars) through the
  ``(n, m)`` stacked-shard layout with zero padding.
- **gradient sync**: the exact reduce-scatter equals
  all-reduce-then-slice (ZeRO-1 ≡ ZeRO-2 on an exact wire); the int8
  wire stays inside the EQuARX amax/127 error bound; non-finite grads
  poison the result so overflow detection fires globally.
- **the step**: a zero-mode
  :class:`~apex_tpu.core.train_state.MixedPrecisionTrainState` trains
  *identically* (to fp32 rounding) to the replicated DP step — Adam
  elementwise, LAMB through the ``shard_axis`` psum'd norms — and a
  planted overflow under fp16 O2 skips GLOBALLY (every shard agrees).
- **placement**: :func:`zero_shardings` puts master/opt shards on the
  ZeRO axis (1/n of the state bytes per device) and everything else
  replicated; :class:`~apex_tpu.resilience.ResilientCheckpointer`
  round-trips the sharded state back onto that placement.

The loss-trajectory band leg lives in ``test_loss_trajectory.py``; the
kill-and-resume arm in ``test_chaos.py``; the HBM/wire A/B in
``bench_configs.bench_bert_o1_zero``.
"""

import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu import amp
from apex_tpu import parallel as apx_parallel
from apex_tpu.optim import fused_adam, fused_lamb
from apex_tpu.parallel import (
    ZeroConfig,
    ZeroOptState,
    all_gather_params,
    distributed_fused_adam,
    distributed_fused_lamb,
    reduce_scatter_mean_grads,
    zero_partition,
    zero_shardings,
    zero_state_specs,
    zero_unpartition,
)

N = 8
AXIS = "fsdp"


def _mesh():
    # raw mesh, deliberately NOT registered with core.mesh (the step
    # is fully manual inside shard_map — test_loss_trajectory.py
    # precedent)
    return Mesh(np.array(jax.devices()[:N]), (AXIS,))


def _mlp_params(seed=0):
    k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
    return {
        "w1": jax.random.normal(k1, (16, 33)) * 0.1,   # 33: pad path
        "b1": jnp.zeros((33,)),
        "w2": jax.random.normal(k2, (33, 1)) * 0.1,
        "b2": jnp.zeros((1,)),
    }


def _mlp_apply(p, x):
    h = jnp.tanh(x @ p["w1"] + p["b1"])
    return h @ p["w2"] + p["b2"]


def _data(seed=3):
    x = jax.random.normal(jax.random.PRNGKey(seed), (64, 16))
    y = jnp.sum(x[:, :4], axis=1, keepdims=True)
    return x, y


def _zero_cfg(**kw):
    kw.setdefault("axis", AXIS)
    kw.setdefault("axis_size", N)
    kw.setdefault("stage", 2)
    return ZeroConfig(**kw)


def _zero_step_fn(specs):
    """Build the canonical zero-mode shard_map train step."""
    def z_step(state, x, y):
        def loss_fn(p):
            cp = state.policy.cast_to_compute(p)
            pred = state.apply_fn(cp, x).astype(jnp.float32)
            loss = jnp.mean((pred - y) ** 2)
            return state.scale_loss(loss), loss

        grads, loss = jax.grad(loss_fn, has_aux=True)(state.params)
        new_state, finite = state.apply_gradients(grads=grads)
        return new_state, jax.lax.pmean(loss, AXIS), finite

    def make(mesh):
        return jax.jit(jax.shard_map(
            z_step, mesh=mesh,
            in_specs=(specs, P(AXIS), P(AXIS)),
            out_specs=(specs, P(), P()), check_vma=False))
    return make


# ------------------------------------------------------------------ layout

class TestPartition:
    @pytest.mark.parametrize("shape", [(33,), (16, 33), (1,), (),
                                       (8, 4), (3, 5, 7)])
    def test_roundtrip(self, shape):
        x = jnp.arange(int(np.prod(shape, initial=1)),
                       dtype=jnp.float32).reshape(shape) + 1.0
        tree = {"x": x}
        shards = zero_partition(tree, N)
        s = shards["x"]
        assert s.shape[0] == N
        assert s.dtype == jnp.float32
        # padding is zeros past the payload
        flat = np.asarray(s).reshape(-1)
        size = int(np.prod(shape, initial=1))
        np.testing.assert_array_equal(flat[size:], 0.0)
        back = zero_unpartition(shards, tree)
        np.testing.assert_array_equal(np.asarray(back["x"]),
                                      np.asarray(x))

    def test_masters_fp32_from_half(self):
        shards = zero_partition({"w": jnp.ones((5,), jnp.bfloat16)}, N)
        assert shards["w"].dtype == jnp.float32

    def test_tree_structure_preserved(self):
        tree = {"a": {"b": jnp.ones((4,)), "c": jnp.ones((2, 2))}}
        shards = zero_partition(tree, N)
        assert jax.tree.structure(shards) == jax.tree.structure(tree)


class TestZeroConfig:
    def test_stage_validated(self):
        with pytest.raises(ValueError, match="stage"):
            _zero_cfg(stage=3).resolved()

    def test_reduce_dtype_validated(self):
        with pytest.raises(ValueError, match="allreduce_dtype"):
            _zero_cfg(reduce_dtype=jnp.int32).resolved()

    def test_axis_size_required_without_mesh(self):
        from apex_tpu.core import mesh as mesh_lib
        mesh_lib.destroy_mesh()
        with pytest.raises((ValueError, RuntimeError)):
            ZeroConfig(axis=AXIS).resolved()

    def test_fp8_moments_rejected(self):
        # fp8_block_scaled lays state across leaf boundaries — not
        # shard-shaped; create must refuse rather than shard garbage
        tx = fused_adam(1e-2, moment_format="fp8_block_scaled")
        with pytest.raises(ValueError, match="shard-shaped"):
            amp.initialize(_mlp_apply, _mlp_params(), tx,
                           opt_level="O0", zero=_zero_cfg())


# ------------------------------------------------------------ grad sync

class TestReduceScatter:
    def _run(self, grads_full, **kw):
        """Reduce-scatter identical per-device grads; return the
        reassembled (n, m) stacked result per leaf."""
        mesh = _mesh()

        def f(g):
            sh = reduce_scatter_mean_grads(g, AXIS, **kw)
            return jax.tree.map(
                lambda s: jax.lax.all_gather(s[0], AXIS, tiled=False),
                sh)

        out = jax.jit(jax.shard_map(
            f, mesh=mesh, in_specs=(P(),), out_specs=P(),
            check_vma=False))(grads_full)
        return out

    def test_exact_equals_partition_of_mean(self):
        g = {"w": jax.random.normal(jax.random.PRNGKey(0), (16, 33)),
             "b": jax.random.normal(jax.random.PRNGKey(1), (33,))}
        got = self._run(g)
        want = zero_partition(g, N)     # mean of n identical == g
        for k in g:
            np.testing.assert_allclose(np.asarray(got[k]),
                                       np.asarray(want[k]),
                                       rtol=0, atol=1e-6)

    def test_stage1_equals_stage2(self):
        g = {"w": jax.random.normal(jax.random.PRNGKey(2), (16, 33))}
        s1 = self._run(g, stage=1)
        s2 = self._run(g, stage=2)
        np.testing.assert_allclose(np.asarray(s1["w"]),
                                   np.asarray(s2["w"]),
                                   rtol=0, atol=1e-6)

    def test_int8_within_amax_bound(self):
        g = {"w": jax.random.normal(jax.random.PRNGKey(3), (16, 33))}
        got = self._run(g, reduce_dtype="int8")
        want = zero_partition(g, N)
        amax = float(jnp.max(jnp.abs(g["w"])))
        # single quantization stage: |err| <= half an int8 step of the
        # global amax (the all-reduce's bound was two stages)
        bound = amax / 127.0
        err = np.abs(np.asarray(got["w"]) - np.asarray(want["w"])).max()
        assert err <= bound, (err, bound)

    def test_half_wire_close(self):
        g = {"w": jax.random.normal(jax.random.PRNGKey(4), (16, 33))}
        got = self._run(g, reduce_dtype=jnp.bfloat16)
        want = zero_partition(g, N)
        np.testing.assert_allclose(np.asarray(got["w"]),
                                   np.asarray(want["w"]),
                                   rtol=0, atol=0.02)

    @pytest.mark.parametrize("reduce_dtype", [None, "int8"])
    def test_nonfinite_poisons_result(self, reduce_dtype):
        g = {"w": jnp.full((16, 33), jnp.inf, jnp.float32)}
        got = self._run(g, reduce_dtype=reduce_dtype)
        assert not np.isfinite(np.asarray(got["w"])).all()


# ------------------------------------------------------------- the step

class TestZeroTrainStep:
    def _run_dp(self, tx, steps=10, opt_level="O0", half=None):
        mesh = _mesh()
        kw = dict(half_dtype=half) if half is not None else {}
        state = amp.initialize(_mlp_apply, _mlp_params(), tx,
                               opt_level=opt_level, **kw)
        x, y = _data()

        def dp_step(state, x, y):
            def loss_fn(p):
                cp = state.policy.cast_to_compute(p)
                pred = state.apply_fn(cp, x).astype(jnp.float32)
                loss = jnp.mean((pred - y) ** 2)
                return state.scale_loss(loss), loss

            grads, loss = jax.grad(loss_fn, has_aux=True)(state.params)
            grads = apx_parallel.all_reduce_mean_grads(grads, AXIS)
            new_state, _ = state.apply_gradients(grads=grads)
            return new_state, jax.lax.pmean(loss, AXIS)

        step = jax.jit(jax.shard_map(
            dp_step, mesh=mesh, in_specs=(P(), P(AXIS), P(AXIS)),
            out_specs=(P(), P()), check_vma=False))
        for _ in range(steps):
            state, loss = step(state, x, y)
        return state, float(loss)

    def _run_zero(self, tx, steps=10, opt_level="O0", half=None, **zkw):
        mesh = _mesh()
        kw = dict(half_dtype=half) if half is not None else {}
        state = amp.initialize(_mlp_apply, _mlp_params(), tx,
                               opt_level=opt_level,
                               zero=_zero_cfg(**zkw), **kw)
        specs = zero_state_specs(state)
        step = _zero_step_fn(specs)(mesh)
        x, y = _data()
        for _ in range(steps):
            state, loss, finite = step(state, x, y)
        return state, float(loss)

    def test_create_layout(self):
        state = amp.initialize(_mlp_apply, _mlp_params(),
                               fused_adam(1e-2), opt_level="O2",
                               zero=_zero_cfg())
        assert isinstance(state.opt_state, ZeroOptState)
        for leaf in jax.tree.leaves(state.opt_state.master):
            assert leaf.dtype == jnp.float32
            assert leaf.shape[0] == N
        # replicated params carry the storage dtype (bf16 under O2) —
        # the fp32 copy lives only in the shards
        assert state.params["w1"].dtype == jnp.bfloat16
        # moments inherit the shard layout
        assert state.opt_state.inner.exp_avg["w1"].shape[0] == N

    def test_zero2_matches_dp_adam(self):
        tx = fused_adam(1e-2)
        sd, ld = self._run_dp(tx)
        sz, lz = self._run_zero(tx)
        for k in sd.params:
            np.testing.assert_allclose(
                np.asarray(sz.params[k]), np.asarray(sd.params[k]),
                rtol=0, atol=2e-6)
        assert abs(ld - lz) < 1e-5

    def test_zero1_matches_zero2_exact_wire(self):
        tx = fused_adam(1e-2)
        s1, _ = self._run_zero(tx, stage=1)
        s2, _ = self._run_zero(tx, stage=2)
        np.testing.assert_allclose(np.asarray(s1.params["w1"]),
                                   np.asarray(s2.params["w1"]),
                                   rtol=0, atol=2e-6)

    def test_zero2_matches_dp_lamb_sharded_norms(self):
        # LAMB's clip + trust ratios psum over the shard axis — the
        # sharded update must equal the full-tensor one
        sd, _ = self._run_dp(fused_lamb(1e-2))
        sz, _ = self._run_zero(
            distributed_fused_lamb(1e-2, shard_axis=AXIS))
        for k in sd.params:
            np.testing.assert_allclose(
                np.asarray(sz.params[k]), np.asarray(sd.params[k]),
                rtol=0, atol=2e-6)

    def test_distributed_fused_adam_is_fused_adam(self):
        tx = distributed_fused_adam(1e-2)
        s1, _ = self._run_zero(tx)
        s2, _ = self._run_zero(fused_adam(1e-2))
        np.testing.assert_allclose(np.asarray(s1.params["w1"]),
                                   np.asarray(s2.params["w1"]),
                                   rtol=0, atol=0)

    def test_int8_wire_trains_close(self):
        tx = fused_adam(1e-2)
        _, l_exact = self._run_zero(tx, steps=20)
        _, l_int8 = self._run_zero(tx, steps=20, reduce_dtype="int8")
        assert abs(l_exact - l_int8) < 0.1, (l_exact, l_int8)

    def test_o2_bf16_masters_stay_fp32_and_train(self):
        tx = fused_adam(1e-2)
        state, loss = self._run_zero(tx, opt_level="O2",
                                     half=jnp.bfloat16, steps=20)
        assert state.opt_state.master["w1"].dtype == jnp.float32
        assert state.params["w1"].dtype == jnp.bfloat16
        _, l0 = self._run_zero(tx, opt_level="O2", half=jnp.bfloat16,
                               steps=1)
        assert loss < l0          # it actually trains

    def test_fp16_overflow_skips_globally(self):
        # a planted overflow on ONE step must skip the update on EVERY
        # shard (the pmin'd flag) and back the scale off exactly like
        # the replicated path; params must be bit-unchanged across the
        # skipped step
        mesh = _mesh()
        tx = fused_adam(1e-2)
        state = amp.initialize(_mlp_apply, _mlp_params(), tx,
                               opt_level="O2", half_dtype=jnp.float16,
                               zero=_zero_cfg())
        specs = zero_state_specs(state)
        x, y = _data()

        def z_step(state, x, y, boost):
            def loss_fn(p):
                cp = state.policy.cast_to_compute(p)
                pred = state.apply_fn(cp, x).astype(jnp.float32)
                loss = jnp.mean((pred - y) ** 2) * boost
                return state.scale_loss(loss), loss

            grads, loss = jax.grad(loss_fn, has_aux=True)(state.params)
            new_state, finite = state.apply_gradients(grads=grads)
            return new_state, finite

        step = jax.jit(jax.shard_map(
            z_step, mesh=mesh,
            in_specs=(specs, P(AXIS), P(AXIS), P()),
            out_specs=(specs, P()), check_vma=False))
        one = jnp.asarray(1.0, jnp.float32)
        # settle the fp16 warmup backoffs (scale 2^16 overflows ~O(1)
        # grads) until a finite step lands
        for _ in range(6):
            state, finite = step(state, x, y, one)
        assert bool(finite)
        before = jax.device_get(state)
        scale_before = float(state.loss_scale_state.loss_scale)
        state, finite = step(state, x, y,
                             jnp.asarray(1e38, jnp.float32))
        assert not bool(finite)
        np.testing.assert_array_equal(
            np.asarray(state.opt_state.master["w1"]),
            np.asarray(before.opt_state.master["w1"]))
        np.testing.assert_array_equal(np.asarray(state.params["w1"]),
                                      np.asarray(before.params["w1"]))
        assert float(state.loss_scale_state.loss_scale) == \
            scale_before * 0.5


# ------------------------------------------------------------- placement

class TestZeroPlacement:
    def _placed_state(self, mesh, tx):
        state = amp.initialize(_mlp_apply, _mlp_params(), tx,
                               opt_level="O2", zero=_zero_cfg())
        return jax.device_put(state, zero_shardings(state, mesh=mesh))

    def test_specs_shape(self):
        state = amp.initialize(_mlp_apply, _mlp_params(),
                               fused_adam(1e-2), opt_level="O0",
                               zero=_zero_cfg())
        specs = zero_state_specs(state)
        assert specs.opt_state.master["w1"] == P(AXIS, None)
        assert specs.opt_state.inner.exp_avg["w1"] == P(AXIS, None)
        assert specs.opt_state.inner.count == P()
        assert specs.params["w1"] == P()
        assert specs.step == P()

    def test_rejects_non_zero_state(self):
        state = amp.initialize(_mlp_apply, _mlp_params(),
                               fused_adam(1e-2), opt_level="O0")
        with pytest.raises(ValueError, match="zero="):
            zero_state_specs(state)

    def test_state_bytes_shrink_n_fold(self):
        # THE point of ZeRO: each device holds 1/n of masters+moments
        mesh = _mesh()
        state = self._placed_state(mesh, fused_adam(1e-2))
        for leaf in jax.tree.leaves(state.opt_state):
            if leaf.ndim == 0:
                continue
            local = leaf.sharding.shard_shape(leaf.shape)
            assert local[0] * N == leaf.shape[0]
        # params stay replicated (full copy per device)
        p = state.params["w1"]
        assert p.sharding.shard_shape(p.shape) == p.shape

    def test_generic_tree_keeps_heuristic(self):
        # the pre-ZeRO generic behavior on plain pytrees is preserved
        mesh = _mesh()
        sh = zero_shardings({"w": jnp.zeros((N * 2, 3))}, axis=AXIS,
                            mesh=mesh)
        assert sh["w"].spec == P(AXIS, None)

    def test_checkpoint_roundtrip_restores_placement(self, tmp_path):
        from apex_tpu.resilience import ResilientCheckpointer

        mesh = _mesh()
        tx = fused_adam(1e-2)
        state = self._placed_state(mesh, tx)
        specs = zero_state_specs(state)
        step = _zero_step_fn(specs)(mesh)
        x, y = _data()
        for _ in range(3):
            state, loss, _ = step(state, x, y)

        ck = ResilientCheckpointer(str(tmp_path), keep=2)
        ck.save(3, state, blocking=False)   # async on-device copies
        ck.wait()
        target = self._placed_state(mesh, tx)
        step_n, restored = ck.restore_latest(target)
        assert step_n == 3
        m = restored.opt_state.master["w1"]
        assert m.sharding.spec == P(AXIS, None)
        assert m.sharding.shard_shape(m.shape)[0] == 1
        np.testing.assert_array_equal(
            np.asarray(m), np.asarray(state.opt_state.master["w1"]))
        # the restored state is step-compatible and bit-identical
        restored, l2, _ = step(restored, x, y)
        state, l1, _ = step(state, x, y)
        assert float(l1) == float(l2)


# -------------------------------------------------- runtime oracle hook

class TestZeroNumcheck:
    def test_strict_flags_half_master_shards(self):
        from apex_tpu.utils import numcheck

        mesh = _mesh()
        numcheck.reset()
        numcheck.instrument(strict=True)
        try:
            state = amp.initialize(_mlp_apply, _mlp_params(),
                                   fused_adam(1e-2), opt_level="O0",
                                   zero=_zero_cfg())
            bad = jax.tree.map(lambda v: v.astype(jnp.bfloat16),
                               state.opt_state.master)
            state = state.replace(
                opt_state=state.opt_state._replace(master=bad))
            specs = zero_state_specs(state)
            step = _zero_step_fn(specs)(mesh)
            x, y = _data()
            step(state, x, y)
            reports = numcheck.reports()
            assert reports, "expected a master-shard violation"
            assert "non-fp32 master shards" in reports[0]
        finally:
            numcheck.uninstrument()
            numcheck.reset()

    def test_strict_clean_on_healthy_zero_step(self):
        from apex_tpu.utils import numcheck

        mesh = _mesh()
        numcheck.reset()
        numcheck.instrument(strict=True)
        try:
            state = amp.initialize(_mlp_apply, _mlp_params(),
                                   fused_adam(1e-2), opt_level="O2",
                                   zero=_zero_cfg())
            specs = zero_state_specs(state)
            step = _zero_step_fn(specs)(mesh)
            x, y = _data()
            for _ in range(3):
                state, _, _ = step(state, x, y)
            jax.effects_barrier()
            numcheck.assert_clean()
            hist = numcheck.site_histograms()
            # fp32 master shards verified at runtime — the histogram
            # records exactly what the optimizer stepped on
            assert set(hist["apply_gradients.master_shards"]) == \
                {"float32"}
        finally:
            numcheck.uninstrument()
            numcheck.reset()
