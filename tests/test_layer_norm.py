"""Golden tests for fused layer norm / RMSNorm — the reference pattern
(``tests/L0/run_fused_layer_norm``): fused kernel vs the eager
composition it replaces, fwd and bwd, across dtypes.  The Pallas kernel
runs in interpret mode on CPU (hermetic); identical code compiles on TPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from apex_tpu import ops

# L0 fast tier: golden kernel/state-machine tests (pytest -m l0)
pytestmark = pytest.mark.l0

H = 256  # lane-aligned hidden size so the Pallas path engages


def _x(rng, shape, dtype=jnp.float32):
    return jnp.asarray(rng.normal(size=shape), dtype)


class TestLayerNormForward:
    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    def test_pallas_vs_reference(self, rng, dtype):
        x = _x(rng, (4, 6, H), dtype)
        w = _x(rng, (H,)) + 1.0
        b = _x(rng, (H,))
        got = ops.fused_layer_norm(x, w, b,
                                   implementation="pallas_interpret")
        want = ops.layer_norm_reference(x, w, b)
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(want, np.float32),
            rtol=1e-5 if dtype == jnp.float32 else 2e-2, atol=1e-5)

    def test_vs_torch(self, rng):
        x = _x(rng, (8, H))
        w = _x(rng, (H,)) + 1.0
        b = _x(rng, (H,))
        got = ops.fused_layer_norm(x, w, b,
                                   implementation="pallas_interpret")
        want = torch.nn.functional.layer_norm(
            torch.tensor(np.asarray(x)), (H,),
            torch.tensor(np.asarray(w)), torch.tensor(np.asarray(b)))
        np.testing.assert_allclose(np.asarray(got), want.numpy(),
                                   rtol=1e-5, atol=1e-5)

    def test_no_affine(self, rng):
        x = _x(rng, (8, H))
        got = ops.fused_layer_norm(x, implementation="pallas_interpret")
        want = ops.layer_norm_reference(x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    def test_unaligned_h_falls_back(self, rng):
        x = _x(rng, (4, 100))  # 100 % 128 != 0 → auto resolves to XLA
        w = _x(rng, (100,))
        got = ops.fused_layer_norm(x, w, implementation="auto")
        want = ops.layer_norm_reference(x, w)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-6)

    def test_ragged_rows(self, rng):
        # rows not a multiple of the block size
        x = _x(rng, (13, H))
        w = _x(rng, (H,))
        b = _x(rng, (H,))
        got = ops.fused_layer_norm(x, w, b,
                                   implementation="pallas_interpret")
        want = ops.layer_norm_reference(x, w, b)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)


class TestLayerNormBackward:
    def test_grads_vs_torch(self, rng):
        x_np = rng.normal(size=(6, H)).astype(np.float32)
        w_np = (rng.normal(size=(H,)) + 1.0).astype(np.float32)
        b_np = rng.normal(size=(H,)).astype(np.float32)
        dy_np = rng.normal(size=(6, H)).astype(np.float32)

        def f(x, w, b):
            y = ops.fused_layer_norm(x, w, b,
                                     implementation="pallas_interpret")
            return jnp.sum(y * jnp.asarray(dy_np))

        dx, dw, db = jax.grad(f, argnums=(0, 1, 2))(
            jnp.asarray(x_np), jnp.asarray(w_np), jnp.asarray(b_np))

        xt = torch.tensor(x_np, requires_grad=True)
        wt = torch.tensor(w_np, requires_grad=True)
        bt = torch.tensor(b_np, requires_grad=True)
        yt = torch.nn.functional.layer_norm(xt, (H,), wt, bt)
        (yt * torch.tensor(dy_np)).sum().backward()

        np.testing.assert_allclose(np.asarray(dx), xt.grad.numpy(),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(dw), wt.grad.numpy(),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(db), bt.grad.numpy(),
                                   rtol=1e-4, atol=1e-4)

    def test_grads_xla_path_match_pallas(self, rng):
        x = _x(rng, (5, H))
        w = _x(rng, (H,)) + 0.5

        def loss(impl):
            def f(x, w):
                return jnp.sum(
                    ops.fused_layer_norm(x, w, implementation=impl) ** 2)
            return jax.grad(f, argnums=(0, 1))(x, w)

        dx_p, dw_p = loss("pallas_interpret")
        dx_x, dw_x = loss("xla")
        np.testing.assert_allclose(np.asarray(dx_p), np.asarray(dx_x),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(dw_p), np.asarray(dw_x),
                                   rtol=1e-4, atol=1e-5)


class TestRMSNorm:
    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    def test_forward(self, rng, dtype):
        x = _x(rng, (4, 3, H), dtype)
        w = _x(rng, (H,)) + 1.0
        got = ops.fused_rms_norm(x, w, implementation="pallas_interpret")
        want = ops.rms_norm_reference(x, w)
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(want, np.float32),
            rtol=1e-5 if dtype == jnp.float32 else 2e-2, atol=1e-5)

    def test_backward_vs_autodiff_of_reference(self, rng):
        x = _x(rng, (6, H))
        w = _x(rng, (H,)) + 1.0

        def f_fused(x, w):
            return jnp.sum(jnp.sin(
                ops.fused_rms_norm(x, w,
                                   implementation="pallas_interpret")))

        def f_ref(x, w):
            return jnp.sum(jnp.sin(ops.rms_norm_reference(x, w)))

        dx_f, dw_f = jax.grad(f_fused, argnums=(0, 1))(x, w)
        dx_r, dw_r = jax.grad(f_ref, argnums=(0, 1))(x, w)
        np.testing.assert_allclose(np.asarray(dx_f), np.asarray(dx_r),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(dw_f), np.asarray(dw_r),
                                   rtol=1e-4, atol=1e-5)

    def test_rms_norm_torch_parity(self, rng):
        x_np = rng.normal(size=(4, H)).astype(np.float32)
        w_np = (rng.normal(size=(H,)) * 0.1 + 1.0).astype(np.float32)
        got = ops.fused_rms_norm(jnp.asarray(x_np), jnp.asarray(w_np),
                                 implementation="pallas_interpret")
        want = torch.nn.functional.rms_norm(
            torch.tensor(x_np), (H,), torch.tensor(w_np), eps=1e-5)
        np.testing.assert_allclose(np.asarray(got), want.numpy(),
                                   rtol=1e-5, atol=1e-5)


class TestMixedPrecisionVariants:
    def test_mixed_fused_half_x_fp32_params(self, rng):
        # MixedFusedLayerNorm parity: half activations, fp32 params
        x = _x(rng, (4, H), jnp.bfloat16)
        w = _x(rng, (H,), jnp.float32) + 1.0
        b = _x(rng, (H,), jnp.float32)
        y = ops.fused_layer_norm(x, w, b,
                                 implementation="pallas_interpret")
        assert y.dtype == jnp.bfloat16
        want = ops.layer_norm_reference(x, w, b)
        np.testing.assert_allclose(np.asarray(y, np.float32),
                                   np.asarray(want, np.float32),
                                   rtol=2e-2, atol=1e-2)
