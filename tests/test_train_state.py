"""End-to-end mixed-precision step tests — the functional analogue of the
reference's L0/run_amp training-loop checks (master weights update, step
skipped on overflow, scaler state persisted)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from apex_tpu import amp
from apex_tpu.core.train_state import MixedPrecisionTrainState
from apex_tpu.core.precision import PrecisionPolicy


def _apply_fn(params, x):
    return x @ params["w"] + params["b"]


def _make_state(opt_level="O2", half_dtype=jnp.float16, **kw):
    params = {"w": jnp.ones((4, 2), jnp.float32) * 0.5,
              "b": jnp.zeros((2,), jnp.float32)}
    tx = optax.sgd(0.1)
    return amp.initialize(_apply_fn, params, tx, opt_level,
                          half_dtype=half_dtype, **kw)


def _loss_fn(params, state, x, y):
    pred = _apply_fn(params, x)
    loss = jnp.mean((pred.astype(jnp.float32) - y) ** 2)
    return amp.scale_loss(loss, state)


def test_o2_masters_are_fp32():
    state = _make_state("O2")
    assert state.params["w"].dtype == jnp.float32
    assert state.compute_params()["w"].dtype == jnp.float16


def test_o3_params_are_half():
    state = _make_state("O3")
    assert state.params["w"].dtype == jnp.float16


def test_step_updates_params():
    # scale 128 (static) so the fp16 grads of the scaled loss stay finite
    state = _make_state("O2", loss_scale=128.0)
    x = jnp.ones((3, 4), jnp.float16)
    y = jnp.zeros((3, 2), jnp.float32)

    @jax.jit
    def step(state, x, y):
        grads = jax.grad(_loss_fn)(state.compute_params(), state, x, y)
        return state.apply_gradients(grads=grads)

    new_state, finite = step(state, x, y)
    assert bool(finite)
    assert int(new_state.step) == 1
    assert not np.allclose(np.asarray(new_state.params["w"]),
                           np.asarray(state.params["w"]))
    # masters stay fp32
    assert new_state.params["w"].dtype == jnp.float32


def test_overflow_skips_step_and_backs_off():
    state = _make_state("O2")
    bad_grads = {"w": jnp.full((4, 2), jnp.nan, jnp.float16),
                 "b": jnp.zeros((2,), jnp.float16)}
    new_state, finite = state.apply_gradients(grads=bad_grads)
    assert not bool(finite)
    np.testing.assert_array_equal(np.asarray(new_state.params["w"]),
                                  np.asarray(state.params["w"]))
    assert float(new_state.loss_scale_state.loss_scale) == 2.0 ** 15
    # step counter still advances (iteration happened)
    assert int(new_state.step) == 1


def test_scaled_loss_value():
    state = _make_state("O2")
    loss = jnp.asarray(1.0)
    assert float(state.scale_loss(loss)) == 2.0 ** 16


def test_unscale_recovers_true_grads():
    state = _make_state("O2", loss_scale=128.0)
    x = jnp.ones((3, 4), jnp.float16)
    y = jnp.zeros((3, 2), jnp.float32)
    # grads of scaled loss
    grads_scaled = jax.grad(_loss_fn)(state.compute_params(), state, x, y)
    grads_ref = jax.grad(
        lambda p: jnp.mean((_apply_fn(p, x).astype(jnp.float32) - y) ** 2)
    )(state.policy.master_params(state.compute_params()))
    ls = state.loss_scaler
    unscaled = ls.unscale(state.loss_scale_state, grads_scaled)
    np.testing.assert_allclose(
        np.asarray(unscaled["w"], np.float32),
        np.asarray(grads_ref["w"], np.float32), rtol=1e-2, atol=1e-3)


def test_amp_state_dict_roundtrip():
    state = _make_state("O2")
    # force a backoff so state is non-default
    state, _ = state.apply_gradients(
        grads={"w": jnp.full((4, 2), jnp.nan, jnp.float16),
               "b": jnp.zeros((2,), jnp.float16)})
    d = amp.state_dict(state)
    fresh = _make_state("O2")
    restored = amp.load_state_dict(fresh, d)
    assert float(restored.loss_scale_state.loss_scale) == \
        float(state.loss_scale_state.loss_scale)


def test_o0_no_scaling_path():
    state = _make_state("O0", half_dtype=jnp.bfloat16)
    x = jnp.ones((3, 4), jnp.float32)
    y = jnp.zeros((3, 2), jnp.float32)
    grads = jax.grad(_loss_fn)(state.compute_params(), state, x, y)
    new_state, finite = state.apply_gradients(grads=grads)
    assert bool(finite)
    assert new_state.params["w"].dtype == jnp.float32


def test_bf16_o2_no_loss_scaling():
    state = _make_state("O2", half_dtype=jnp.bfloat16)
    assert not state.policy.needs_loss_scaling
    assert state.compute_params()["w"].dtype == jnp.bfloat16
