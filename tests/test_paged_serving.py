"""Paged serving datapath (apex_tpu.serving.PagedEngine).

Correctness contracts under test:

- greedy decode through the paged engine is TOKEN-IDENTICAL to
  ``generate()`` for prompt lengths straddling every boundary that
  matters (page size, chunk size, and their multiples);
- a steady-state soak of mixed chunked-prefill + decode traffic with
  heterogeneous sampling params triggers ZERO retraces after warmup at
  the EXACT documented budget — decode_step/prefill_step/admit/release
  = 1 each (the dense engine's per-bucket prefills collapse to one
  mixed-step shape);
- the block allocator: fragmentation-tolerant reuse, atomic
  exhaustion, double-free detection, the reserved null page;
- token-budget admission (free pages must cover prompt + headroom)
  and block-exhaustion preemption that requeues the evicted tenant to
  continue from its streamed prefix — with the greedy chain still
  token-identical end to end;
- eviction releases pages (deadline/fault paths reuse the same
  release), sampled chains are a function of the request's own seed,
  and the server surfaces TTFT / step-latency percentiles and the
  blocks-occupancy gauge.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu.models import GPTConfig, GPTModel, LlamaConfig, LlamaModel, generate
from apex_tpu.serving import (
    BlockAllocator,
    BlockExhausted,
    InferenceServer,
    PagedEngine,
    Request,
    Scheduler,
)
from apex_tpu.serving import cache as slot_cache
from apex_tpu.utils import MetricsWriter, tracecheck


def _tiny_gpt():
    cfg = GPTConfig.tiny(position_embedding="learned",
                         scan_layers=True)
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))
    return model, {"params": params["params"]}


def _tiny_llama():
    cfg = LlamaConfig.tiny(scan_layers=True)
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))
    return model, {"params": params["params"]}


@pytest.fixture(scope="module")
def gpt():
    return _tiny_gpt()


@pytest.fixture(scope="module")
def llama():
    return _tiny_llama()


class TestBlockAllocator:
    def test_null_page_reserved_and_sizes(self):
        alloc = BlockAllocator(9, 4)
        assert alloc.blocks_total == 8
        assert alloc.tokens_total == 32
        got = alloc.alloc(8)
        assert 0 not in got and len(set(got)) == 8
        assert alloc.blocks_free == 0

    def test_fragmented_interleave_reuses_everything(self):
        """Interleaved alloc/free in awkward sizes: a paged pool has
        no fragmentation — any n <= free succeeds regardless of WHICH
        pages were returned."""
        alloc = BlockAllocator(17, 8)
        a = alloc.alloc(5)
        b = alloc.alloc(7)
        alloc.free(a[1:4])          # punch holes
        c = alloc.alloc(3)          # reuses the holes
        assert set(c) == set(a[1:4])
        alloc.free(b)
        alloc.free(c)
        alloc.free([a[0], a[4]])
        assert alloc.blocks_free == alloc.blocks_total == 16
        assert set(alloc.alloc(16)) == set(range(1, 17))

    def test_exhaustion_is_atomic(self):
        alloc = BlockAllocator(5, 2)
        alloc.alloc(3)
        with pytest.raises(BlockExhausted):
            alloc.alloc(2)
        # the failed alloc took nothing
        assert alloc.blocks_free == 1
        assert alloc.alloc(1)

    def test_double_free_and_bad_range_raise(self):
        alloc = BlockAllocator(5, 2)
        got = alloc.alloc(2)
        alloc.free(got)
        with pytest.raises(ValueError, match="double free"):
            alloc.free([got[0]])
        with pytest.raises(ValueError, match="range"):
            alloc.free([0])

    def test_blocks_for(self):
        assert slot_cache.blocks_for(1, 8) == 1
        assert slot_cache.blocks_for(8, 8) == 1
        assert slot_cache.blocks_for(9, 8) == 2


class TestGreedyParityAcrossBoundaries:
    # [the llama twin is slow-marked: ~40s of CPU compile for the same
    # engine property the gpt twin pins in tier-1 (GQA decode parity
    # is separately tier-1-covered by test_generate's incremental
    # suites); it still runs under -m slow and in the on-chip pass]
    @pytest.mark.l0
    @pytest.mark.parametrize("which", [
        "gpt", pytest.param("llama", marks=pytest.mark.slow)])
    def test_engine_matches_generate(self, which, request):
        """block_size=8, chunk=4: prompt lengths straddle the page
        boundary (7/8/9), the chunk boundary (3/4/5), their common
        multiples (15/16/17) and a multi-page prompt (23) — every
        chain must reproduce generate() exactly, including requests
        that queue behind the first wave."""
        model, params = request.getfixturevalue(which)
        rng = np.random.default_rng(3)
        lengths = (7, 8, 9, 3, 4, 5, 15, 16, 17, 23)
        budgets = [6, 3, 5, 7, 4, 8, 3, 5, 6, 4]
        prompts = [rng.integers(0, model.cfg.vocab_size,
                                size=(L,)).astype(np.int32)
                   for L in lengths]
        engine = PagedEngine(model, params, max_slots=3, block_size=8,
                             prefill_chunk=4)
        sched = Scheduler(engine)
        reqs = [sched.submit(Request(prompt=p, max_new_tokens=n))
                for p, n in zip(prompts, budgets)]
        sched.drain()
        for p, n, r in zip(prompts, budgets, reqs):
            ref = np.asarray(generate(
                model, params, jnp.asarray(p[None]),
                max_new_tokens=n))[0, len(p):]
            np.testing.assert_array_equal(
                np.asarray(r.tokens), ref,
                err_msg=f"{which} prompt_len={len(p)} n={n}")
        assert engine.blocks_in_use == 0

    def test_tenant_near_max_seq_len_survives_cotenant_prefill(self):
        """Regression (review finding): a tenant decoding within one
        chunk of max_seq_len rides a WIDE mixed step when a co-tenant
        chunk-prefills; its pad positions past max_seq_len must land
        in the null page, NOT wrap into its last live block (the old
        clamp overwrote visible K/V and flipped late greedy tokens)."""
        import dataclasses

        cfg = dataclasses.replace(
            GPTConfig.tiny(position_embedding="learned",
                           scan_layers=True), max_seq_len=16)
        model = GPTModel(cfg)
        params = {"params": model.init(
            jax.random.PRNGKey(0),
            jnp.zeros((1, 4), jnp.int32))["params"]}
        rng = np.random.default_rng(5)
        pa = rng.integers(0, cfg.vocab_size, size=(2,)).astype(np.int32)
        ref_a = np.asarray(generate(
            model, params, jnp.asarray(pa[None]),
            max_new_tokens=14))[0, 2:]          # fills the cache: 2+14=16
        engine = PagedEngine(model, params, max_slots=2, block_size=8,
                             prefill_chunk=4)
        sched = Scheduler(engine)
        ra = sched.submit(Request(prompt=pa, max_new_tokens=14))
        for _ in range(10):                     # decode A near the end
            sched.run_step()
        pb = rng.integers(0, cfg.vocab_size,
                          size=(10,)).astype(np.int32)
        rb = sched.submit(Request(prompt=pb, max_new_tokens=2))
        sched.drain()
        np.testing.assert_array_equal(np.asarray(ra.tokens), ref_a)
        ref_b = np.asarray(generate(
            model, params, jnp.asarray(pb[None]),
            max_new_tokens=2))[0, 10:]
        np.testing.assert_array_equal(np.asarray(rb.tokens), ref_b)

    def test_eos_stops_early_and_matches_generate(self, gpt):
        model, params = gpt
        rng = np.random.default_rng(5)
        prompt = rng.integers(0, model.cfg.vocab_size,
                              size=(9,)).astype(np.int32)
        n = 8
        ref = np.asarray(generate(
            model, params, jnp.asarray(prompt[None]),
            max_new_tokens=n))[0, 9:]
        eos = int(ref[2])
        engine = PagedEngine(model, params, max_slots=1, block_size=8,
                             prefill_chunk=4)
        sched = Scheduler(engine)
        req = sched.submit(Request(prompt=prompt, max_new_tokens=n,
                                   eos_id=eos))
        sched.drain()
        got = np.asarray(req.tokens)
        first = int(np.argmax(ref == eos))
        np.testing.assert_array_equal(got, ref[:first + 1])
        assert got[-1] == eos and len(got) < n


class TestSoakZeroRetraces:
    def test_mixed_chunked_prefill_decode_soak(self, gpt):
        """The acceptance soak: chunked-prefill admissions interleave
        with steady decode across 14 requests / 3 slots, mixed
        temperature / top_k / top_p / eos / budgets — zero jaxpr
        traces after warmup, and the guards pin the budget to the
        documented constants: decode_step = prefill_step = admit =
        release = 1."""
        model, params = gpt
        engine = PagedEngine(model, params, max_slots=3, block_size=8,
                             prefill_chunk=4)
        sched = Scheduler(engine)
        engine.warmup()
        assert engine.trace_counts == {
            "decode_step": 1, "prefill_step": 1, "admit": 1,
            "release": 1}

        rng = np.random.default_rng(11)
        before = tracecheck.trace_event_count()
        cases = [
            (3, 4, 0.0, None, None, None),
            (7, 3, 0.8, 20, None, None),
            (12, 5, 1.2, 5, None, 0.9), (2, 6, 0.0, None, 17, None),
            (8, 2, 0.5, None, None, 0.5),
            (16, 4, 0.0, None, None, None),
            (5, 3, 1.0, 50, 3, 0.95), (4, 5, 0.0, None, None, None),
            (9, 4, 0.7, 10, None, None), (1, 2, 0.0, None, None, None),
            (13, 3, 1.5, 2, None, 1.0), (6, 6, 0.0, None, 900, None),
            (11, 2, 0.9, None, None, 0.7),
            (8, 4, 0.0, None, None, None),
        ]
        reqs = []
        for i, (L, n, t, k, eos, p) in enumerate(cases):
            reqs.append(sched.submit(Request(
                prompt=rng.integers(0, model.cfg.vocab_size,
                                    size=(L,)).astype(np.int32),
                max_new_tokens=n, temperature=t, top_k=k, top_p=p,
                eos_id=eos, seed=i)))
        events = sched.drain()
        assert tracecheck.trace_event_count() == before, (
            "steady-state paged soak retraced after warmup")
        assert engine.trace_counts == {
            "decode_step": 1, "prefill_step": 1, "admit": 1,
            "release": 1}
        for (L, n, t, k, eos, p), r in zip(cases, reqs):
            assert 1 <= len(r.tokens) <= n
            if eos is None:
                assert len(r.tokens) == n
        assert len(events) == sum(len(r.tokens) for r in reqs)
        assert engine.blocks_in_use == 0


class TestTokenBudgetAdmission:
    def test_can_admit_gates_on_free_pages(self, gpt):
        model, params = gpt
        engine = PagedEngine(model, params, max_slots=4, block_size=8,
                             pool_tokens=64, prefill_chunk=4,
                             admit_headroom=8)
        # empty pool: plenty of room
        assert engine.can_admit(16, 8)
        # occupy almost everything via a long tenant
        engine.admit(0, np.zeros(40, np.int32), max_new_tokens=8)
        while engine._tenants[0] is not None \
                and engine._tenants[0].fed < 40:
            engine.step()
        assert engine.blocks_in_use >= 5
        # 3 free pages (24 tokens) left: 18+8 tokens of prompt +
        # headroom need a 4th page — blocked; 16+8 exactly fits
        assert not engine.can_admit(18, 8)
        assert engine.can_admit(16, 8)
        engine.release(0)
        assert engine.blocks_in_use == 0

    def test_request_bigger_than_pool_rejected_at_submit(self, gpt):
        model, params = gpt
        engine = PagedEngine(model, params, max_slots=1, block_size=8,
                             pool_tokens=32, prefill_chunk=4)
        sched = Scheduler(engine)
        with pytest.raises(ValueError, match="pool"):
            sched.submit(Request(prompt=np.zeros(30, np.int32),
                                 max_new_tokens=10))
        # and the usual envelope checks still apply
        with pytest.raises(ValueError, match="max_seq_len"):
            engine.validate_request(8, model.cfg.max_seq_len)
        with pytest.raises(ValueError, match="top_k"):
            engine.validate_request(4, 2,
                                    top_k=model.cfg.vocab_size + 1)

    def test_occupied_slot_rejected(self, gpt):
        model, params = gpt
        engine = PagedEngine(model, params, max_slots=1, block_size=8,
                             prefill_chunk=4)
        engine.admit(0, np.zeros(4, np.int32), max_new_tokens=2)
        with pytest.raises(ValueError, match="occupied"):
            engine.admit(0, np.zeros(4, np.int32), max_new_tokens=2)


class TestPreemption:
    def test_exhaustion_preempts_requeues_and_stays_token_identical(
            self, gpt):
        """Two tenants overcommit a pool that cannot hold both live
        sequences: the youngest is preempted (pages freed), requeued,
        and continues from its streamed prefix — both greedy chains
        still match generate() token for token, and the pool drains
        to zero."""
        model, params = gpt
        engine = PagedEngine(model, params, max_slots=2, block_size=8,
                             pool_tokens=64, prefill_chunk=4,
                             admit_headroom=0)
        sched = Scheduler(engine)
        engine.warmup()
        rng = np.random.default_rng(7)
        p1 = rng.integers(0, model.cfg.vocab_size,
                          size=(20,)).astype(np.int32)
        p2 = rng.integers(0, model.cfg.vocab_size,
                          size=(22,)).astype(np.int32)
        r1 = sched.submit(Request(prompt=p1, max_new_tokens=30))
        r2 = sched.submit(Request(prompt=p2, max_new_tokens=28))
        sched.drain()
        assert sched.preempts >= 1
        for p, n, r in ((p1, 30, r1), (p2, 28, r2)):
            ref = np.asarray(generate(
                model, params, jnp.asarray(p[None]),
                max_new_tokens=n))[0, len(p):]
            np.testing.assert_array_equal(np.asarray(r.tokens), ref)
        assert engine.blocks_in_use == 0
        # recovery replays compiled programs — budgets untouched
        assert engine.trace_counts == {
            "decode_step": 1, "prefill_step": 1, "admit": 1,
            "release": 1}

    def test_eviction_releases_blocks(self, gpt):
        """scheduler.evict (the deadline/fault path) returns every
        page to the pool."""
        model, params = gpt
        engine = PagedEngine(model, params, max_slots=2, block_size=8,
                             prefill_chunk=4)
        sched = Scheduler(engine)
        sched.submit(Request(prompt=np.zeros(12, np.int32),
                             max_new_tokens=50))
        for _ in range(6):
            sched.run_step()
        assert engine.blocks_in_use >= 2
        assert sched.active_count == 1
        sched.evict(0)
        assert engine.blocks_in_use == 0
        assert sched.active_count == 0


class TestSamplingDeterminism:
    def test_tokens_independent_of_cotenants(self, gpt):
        """A sampled request's chain is a function of its own seed —
        co-tenant traffic (and the chunked prefill it causes) must not
        perturb it: the k-th produced token always consumes the k-th
        rng split (emission-gated rng advance)."""
        model, params = gpt
        rng = np.random.default_rng(7)
        prompt = rng.integers(0, model.cfg.vocab_size,
                              size=(6,)).astype(np.int32)

        def run(extra_traffic):
            engine = PagedEngine(model, params, max_slots=2,
                                 block_size=8, prefill_chunk=4)
            sched = Scheduler(engine)
            req = sched.submit(Request(
                prompt=prompt, max_new_tokens=5, temperature=0.9,
                top_k=20, seed=123))
            if extra_traffic:
                for i in range(3):
                    sched.submit(Request(
                        prompt=rng.integers(
                            0, model.cfg.vocab_size,
                            size=(4 + i,)).astype(np.int32),
                        max_new_tokens=4, temperature=1.3, seed=i))
            sched.drain()
            return list(req.tokens)

        assert run(False) == run(True)


class TestPagedServer:
    def test_streaming_parity_metrics_and_gauges(self, gpt):
        model, params = gpt
        rng = np.random.default_rng(13)
        prompt = rng.integers(0, model.cfg.vocab_size,
                              size=(9,)).astype(np.int32)
        ref = np.asarray(generate(
            model, params, jnp.asarray(prompt[None]),
            max_new_tokens=5))[0, 9:]
        rows = []
        writer = MetricsWriter(sink=lambda s, m: rows.append((s, m)))
        server = InferenceServer(
            model, params, max_slots=2, kv_cache="paged", block_size=8,
            prefill_chunk=4, metrics=writer, metrics_interval=2)
        with server:
            h1 = server.submit(prompt, max_new_tokens=5)
            h2 = server.submit(
                rng.integers(0, model.cfg.vocab_size, size=(6,)),
                max_new_tokens=3, temperature=0.8, seed=4)
            got = h1.result(timeout=300)
            assert len(h2.result(timeout=300)) == 3
            health = server.health()
        np.testing.assert_array_equal(np.asarray(got), ref)
        # the occupancy gauge and latency percentiles ride health +
        # every metrics emission
        assert health["blocks_total"] == server.engine.blocks_total
        assert health["blocks_in_use"] == 0
        assert health["preempts"] == 0
        assert rows, "metrics never emitted"
        merged = {}
        for _, m in rows:
            merged.update(m)
        assert {"tokens_per_sec", "occupancy", "queue_depth",
                "blocks_in_use", "blocks_total", "ttft_p50_s",
                "ttft_p99_s", "step_ms_p50",
                "step_ms_p99"} <= set(merged)
        assert merged["ttft_p50_s"] > 0
        summary = server.latency_summary()
        assert summary["ttft_p99_s"] >= summary["ttft_p50_s"]

    def test_invalid_kv_cache_rejected(self, gpt):
        model, params = gpt
        with pytest.raises(ValueError, match="kv_cache"):
            InferenceServer(model, params, kv_cache="sparse")


class TestTrafficModel:
    def test_serving_traffic_model_scales_with_live_tokens(self):
        """The analytic per-step KV traffic model (bench_configs):
        dense bytes pinned at max_seq_len, paged bytes ∝ live pages;
        the paged pool footprint is sized in tokens."""
        import bench_configs as bc

        cfg = dict(num_layers=4, kv_heads=2, head_dim=64,
                   max_seq_len=2048, dtype_bytes=2, slots=8,
                   block_size=16)
        small = bc._serving_traffic_model(live_tokens=128, **cfg)
        big = bc._serving_traffic_model(live_tokens=512, **cfg)
        for out in (small, big):
            assert {"dense_kv_read_bytes_per_step",
                    "paged_kv_read_bytes_per_step",
                    "dense_pool_bytes", "paged_pool_tokens"} <= set(out)
        # dense per-step reads are live-independent; paged scale ~4x
        assert small["dense_kv_read_bytes_per_step"] \
            == big["dense_kv_read_bytes_per_step"]
        ratio = (big["paged_kv_read_bytes_per_step"]
                 / small["paged_kv_read_bytes_per_step"])
        assert 3.5 <= ratio <= 4.5
        assert small["paged_kv_read_bytes_per_step"] \
            < small["dense_kv_read_bytes_per_step"]
