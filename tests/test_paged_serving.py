"""Paged serving datapath (apex_tpu.serving.PagedEngine).

Correctness contracts under test:

- greedy decode through the paged engine is TOKEN-IDENTICAL to
  ``generate()`` for prompt lengths straddling every boundary that
  matters (page size, chunk size, and their multiples);
- a steady-state soak of mixed chunked-prefill + decode traffic with
  heterogeneous sampling params triggers ZERO retraces after warmup at
  the EXACT documented budget — decode_step/prefill_step/admit/release
  = 1 each (the dense engine's per-bucket prefills collapse to one
  mixed-step shape);
- the block allocator: fragmentation-tolerant reuse, atomic
  exhaustion, double-free detection, the reserved null page;
- token-budget admission (free pages must cover prompt + headroom)
  and block-exhaustion preemption that requeues the evicted tenant to
  continue from its streamed prefix — with the greedy chain still
  token-identical end to end;
- eviction releases pages (deadline/fault paths reuse the same
  release), sampled chains are a function of the request's own seed,
  and the server surfaces TTFT / step-latency percentiles and the
  blocks-occupancy gauge;
- copy-on-write prefix sharing (ISSUE 7): refcounted page sharing of
  trie-matched prompt prefixes, CoW fork at whole-prompt hits, exact
  ``blocks_in_use`` accounting under sharing, shared-aware admission,
  and greedy chains token-identical with sharing on;
- speculative decoding (ISSUE 7): the prompt-lookup drafter, the
  one-application K-token verify, acceptance-invariant greedy AND
  sampled chains, the accept-rate gauge, and the 5-executable /
  zero-retrace budget with drafting on;
- quantized KV pages (ISSUE 8): ``kv_dtype="int8"``/``"fp8"`` pool
  storage with per-(kv_head, page) amax scales — the ≥1.9× equal-HBM
  capacity default, scale reset on page reuse (deterministic replay on
  a dirty pool), sharing/CoW/spec riding quantized pages
  token-identically to an unshared quantized run, the 5×1 trace budget
  with quantization on, kv_dtype/kv_bits in health()+metrics, the
  "auto" pair pickup from the autotune table, and (slow tier) ≥95%
  greedy token agreement vs ``generate()`` on a trained proxy.
  ``kv_dtype=None`` byte-identity is pinned by this whole module: every
  other test here runs the default unquantized pool through the same
  code path.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu.models import GPTConfig, GPTModel, LlamaConfig, LlamaModel, generate
from apex_tpu.serving import (
    BlockAllocator,
    BlockExhausted,
    InferenceServer,
    PagedEngine,
    PrefixTrie,
    Request,
    Scheduler,
    chain_digests,
    prompt_lookup_draft,
)
from apex_tpu.serving import cache as slot_cache
from apex_tpu.utils import MetricsWriter, tracecheck


def _tiny_gpt():
    cfg = GPTConfig.tiny(position_embedding="learned",
                         scan_layers=True)
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))
    return model, {"params": params["params"]}


def _tiny_llama():
    cfg = LlamaConfig.tiny(scan_layers=True)
    model = LlamaModel(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))
    return model, {"params": params["params"]}


@pytest.fixture(scope="module")
def gpt():
    return _tiny_gpt()


@pytest.fixture(scope="module")
def llama():
    return _tiny_llama()


class TestBlockAllocator:
    def test_null_page_reserved_and_sizes(self):
        alloc = BlockAllocator(9, 4)
        assert alloc.blocks_total == 8
        assert alloc.tokens_total == 32
        got = alloc.alloc(8)
        assert 0 not in got and len(set(got)) == 8
        assert alloc.blocks_free == 0

    def test_fragmented_interleave_reuses_everything(self):
        """Interleaved alloc/free in awkward sizes: a paged pool has
        no fragmentation — any n <= free succeeds regardless of WHICH
        pages were returned."""
        alloc = BlockAllocator(17, 8)
        a = alloc.alloc(5)
        b = alloc.alloc(7)
        alloc.free(a[1:4])          # punch holes
        c = alloc.alloc(3)          # reuses the holes
        assert set(c) == set(a[1:4])
        alloc.free(b)
        alloc.free(c)
        alloc.free([a[0], a[4]])
        assert alloc.blocks_free == alloc.blocks_total == 16
        assert set(alloc.alloc(16)) == set(range(1, 17))

    def test_exhaustion_is_atomic(self):
        alloc = BlockAllocator(5, 2)
        alloc.alloc(3)
        with pytest.raises(BlockExhausted):
            alloc.alloc(2)
        # the failed alloc took nothing
        assert alloc.blocks_free == 1
        assert alloc.alloc(1)

    def test_double_free_and_bad_range_raise(self):
        alloc = BlockAllocator(5, 2)
        got = alloc.alloc(2)
        alloc.free(got)
        with pytest.raises(ValueError, match="double free"):
            alloc.free([got[0]])
        with pytest.raises(ValueError, match="range"):
            alloc.free([0])

    def test_blocks_for(self):
        assert slot_cache.blocks_for(1, 8) == 1
        assert slot_cache.blocks_for(8, 8) == 1
        assert slot_cache.blocks_for(9, 8) == 2


class TestGreedyParityAcrossBoundaries:
    # [the llama twin is slow-marked: ~40s of CPU compile for the same
    # engine property the gpt twin pins in tier-1 (GQA decode parity
    # is separately tier-1-covered by test_generate's incremental
    # suites); it still runs under -m slow and in the on-chip pass]
    @pytest.mark.l0
    @pytest.mark.parametrize("which", [
        "gpt", pytest.param("llama", marks=pytest.mark.slow)])
    def test_engine_matches_generate(self, which, request):
        """block_size=8, chunk=4: prompt lengths straddle the page
        boundary (7/8/9), the chunk boundary (3/4/5), their common
        multiples (15/16/17) and a multi-page prompt (23) — every
        chain must reproduce generate() exactly, including requests
        that queue behind the first wave."""
        model, params = request.getfixturevalue(which)
        rng = np.random.default_rng(3)
        lengths = (7, 8, 9, 3, 4, 5, 15, 16, 17, 23)
        budgets = [6, 3, 5, 7, 4, 8, 3, 5, 6, 4]
        prompts = [rng.integers(0, model.cfg.vocab_size,
                                size=(L,)).astype(np.int32)
                   for L in lengths]
        engine = PagedEngine(model, params, max_slots=3, block_size=8,
                             prefill_chunk=4)
        sched = Scheduler(engine)
        reqs = [sched.submit(Request(prompt=p, max_new_tokens=n))
                for p, n in zip(prompts, budgets)]
        sched.drain()
        for p, n, r in zip(prompts, budgets, reqs):
            ref = np.asarray(generate(
                model, params, jnp.asarray(p[None]),
                max_new_tokens=n))[0, len(p):]
            np.testing.assert_array_equal(
                np.asarray(r.tokens), ref,
                err_msg=f"{which} prompt_len={len(p)} n={n}")
        assert engine.blocks_in_use == 0

    def test_tenant_near_max_seq_len_survives_cotenant_prefill(self):
        """Regression (review finding): a tenant decoding within one
        chunk of max_seq_len rides a WIDE mixed step when a co-tenant
        chunk-prefills; its pad positions past max_seq_len must land
        in the null page, NOT wrap into its last live block (the old
        clamp overwrote visible K/V and flipped late greedy tokens)."""
        import dataclasses

        cfg = dataclasses.replace(
            GPTConfig.tiny(position_embedding="learned",
                           scan_layers=True), max_seq_len=16)
        model = GPTModel(cfg)
        params = {"params": model.init(
            jax.random.PRNGKey(0),
            jnp.zeros((1, 4), jnp.int32))["params"]}
        rng = np.random.default_rng(5)
        pa = rng.integers(0, cfg.vocab_size, size=(2,)).astype(np.int32)
        ref_a = np.asarray(generate(
            model, params, jnp.asarray(pa[None]),
            max_new_tokens=14))[0, 2:]          # fills the cache: 2+14=16
        engine = PagedEngine(model, params, max_slots=2, block_size=8,
                             prefill_chunk=4)
        sched = Scheduler(engine)
        ra = sched.submit(Request(prompt=pa, max_new_tokens=14))
        for _ in range(10):                     # decode A near the end
            sched.run_step()
        pb = rng.integers(0, cfg.vocab_size,
                          size=(10,)).astype(np.int32)
        rb = sched.submit(Request(prompt=pb, max_new_tokens=2))
        sched.drain()
        np.testing.assert_array_equal(np.asarray(ra.tokens), ref_a)
        ref_b = np.asarray(generate(
            model, params, jnp.asarray(pb[None]),
            max_new_tokens=2))[0, 10:]
        np.testing.assert_array_equal(np.asarray(rb.tokens), ref_b)

    def test_eos_stops_early_and_matches_generate(self, gpt):
        model, params = gpt
        rng = np.random.default_rng(5)
        prompt = rng.integers(0, model.cfg.vocab_size,
                              size=(9,)).astype(np.int32)
        n = 8
        ref = np.asarray(generate(
            model, params, jnp.asarray(prompt[None]),
            max_new_tokens=n))[0, 9:]
        eos = int(ref[2])
        engine = PagedEngine(model, params, max_slots=1, block_size=8,
                             prefill_chunk=4)
        sched = Scheduler(engine)
        req = sched.submit(Request(prompt=prompt, max_new_tokens=n,
                                   eos_id=eos))
        sched.drain()
        got = np.asarray(req.tokens)
        first = int(np.argmax(ref == eos))
        np.testing.assert_array_equal(got, ref[:first + 1])
        assert got[-1] == eos and len(got) < n


class TestSoakZeroRetraces:
    def test_mixed_chunked_prefill_decode_soak(self, gpt):
        """The acceptance soak: chunked-prefill admissions interleave
        with steady decode across 14 requests / 3 slots, mixed
        temperature / top_k / top_p / eos / budgets — zero jaxpr
        traces after warmup, and the guards pin the budget to the
        documented constants: decode_step = prefill_step = admit =
        release = 1."""
        model, params = gpt
        engine = PagedEngine(model, params, max_slots=3, block_size=8,
                             prefill_chunk=4)
        sched = Scheduler(engine)
        engine.warmup()
        assert engine.trace_counts == {
            "decode_step": 1, "prefill_step": 1, "admit": 1,
            "release": 1}

        rng = np.random.default_rng(11)
        before = tracecheck.trace_event_count()
        cases = [
            (3, 4, 0.0, None, None, None),
            (7, 3, 0.8, 20, None, None),
            (12, 5, 1.2, 5, None, 0.9), (2, 6, 0.0, None, 17, None),
            (8, 2, 0.5, None, None, 0.5),
            (16, 4, 0.0, None, None, None),
            (5, 3, 1.0, 50, 3, 0.95), (4, 5, 0.0, None, None, None),
            (9, 4, 0.7, 10, None, None), (1, 2, 0.0, None, None, None),
            (13, 3, 1.5, 2, None, 1.0), (6, 6, 0.0, None, 900, None),
            (11, 2, 0.9, None, None, 0.7),
            (8, 4, 0.0, None, None, None),
        ]
        reqs = []
        for i, (L, n, t, k, eos, p) in enumerate(cases):
            reqs.append(sched.submit(Request(
                prompt=rng.integers(0, model.cfg.vocab_size,
                                    size=(L,)).astype(np.int32),
                max_new_tokens=n, temperature=t, top_k=k, top_p=p,
                eos_id=eos, seed=i)))
        events = sched.drain()
        assert tracecheck.trace_event_count() == before, (
            "steady-state paged soak retraced after warmup")
        assert engine.trace_counts == {
            "decode_step": 1, "prefill_step": 1, "admit": 1,
            "release": 1}
        for (L, n, t, k, eos, p), r in zip(cases, reqs):
            assert 1 <= len(r.tokens) <= n
            if eos is None:
                assert len(r.tokens) == n
        assert len(events) == sum(len(r.tokens) for r in reqs)
        assert engine.blocks_in_use == 0


class TestTokenBudgetAdmission:
    def test_can_admit_gates_on_free_pages(self, gpt):
        model, params = gpt
        engine = PagedEngine(model, params, max_slots=4, block_size=8,
                             pool_tokens=64, prefill_chunk=4,
                             admit_headroom=8)
        # empty pool: plenty of room
        assert engine.can_admit(16, 8)
        # occupy almost everything via a long tenant
        engine.admit(0, np.zeros(40, np.int32), max_new_tokens=8)
        while engine._tenants[0] is not None \
                and engine._tenants[0].fed < 40:
            engine.step()
        assert engine.blocks_in_use >= 5
        # 3 free pages (24 tokens) left: 18+8 tokens of prompt +
        # headroom need a 4th page — blocked; 16+8 exactly fits
        assert not engine.can_admit(18, 8)
        assert engine.can_admit(16, 8)
        engine.release(0)
        assert engine.blocks_in_use == 0

    def test_request_bigger_than_pool_rejected_at_submit(self, gpt):
        model, params = gpt
        engine = PagedEngine(model, params, max_slots=1, block_size=8,
                             pool_tokens=32, prefill_chunk=4)
        sched = Scheduler(engine)
        with pytest.raises(ValueError, match="pool"):
            sched.submit(Request(prompt=np.zeros(30, np.int32),
                                 max_new_tokens=10))
        # and the usual envelope checks still apply
        with pytest.raises(ValueError, match="max_seq_len"):
            engine.validate_request(8, model.cfg.max_seq_len)
        with pytest.raises(ValueError, match="top_k"):
            engine.validate_request(4, 2,
                                    top_k=model.cfg.vocab_size + 1)

    def test_occupied_slot_rejected(self, gpt):
        model, params = gpt
        engine = PagedEngine(model, params, max_slots=1, block_size=8,
                             prefill_chunk=4)
        engine.admit(0, np.zeros(4, np.int32), max_new_tokens=2)
        with pytest.raises(ValueError, match="occupied"):
            engine.admit(0, np.zeros(4, np.int32), max_new_tokens=2)


class TestPreemption:
    def test_exhaustion_preempts_requeues_and_stays_token_identical(
            self, gpt):
        """Two tenants overcommit a pool that cannot hold both live
        sequences: the youngest is preempted (pages freed), requeued,
        and continues from its streamed prefix — both greedy chains
        still match generate() token for token, and the pool drains
        to zero."""
        model, params = gpt
        engine = PagedEngine(model, params, max_slots=2, block_size=8,
                             pool_tokens=64, prefill_chunk=4,
                             admit_headroom=0)
        sched = Scheduler(engine)
        engine.warmup()
        rng = np.random.default_rng(7)
        p1 = rng.integers(0, model.cfg.vocab_size,
                          size=(20,)).astype(np.int32)
        p2 = rng.integers(0, model.cfg.vocab_size,
                          size=(22,)).astype(np.int32)
        r1 = sched.submit(Request(prompt=p1, max_new_tokens=30))
        r2 = sched.submit(Request(prompt=p2, max_new_tokens=28))
        sched.drain()
        assert sched.preempts >= 1
        for p, n, r in ((p1, 30, r1), (p2, 28, r2)):
            ref = np.asarray(generate(
                model, params, jnp.asarray(p[None]),
                max_new_tokens=n))[0, len(p):]
            np.testing.assert_array_equal(np.asarray(r.tokens), ref)
        assert engine.blocks_in_use == 0
        # recovery replays compiled programs — budgets untouched
        assert engine.trace_counts == {
            "decode_step": 1, "prefill_step": 1, "admit": 1,
            "release": 1}

    def test_eviction_releases_blocks(self, gpt):
        """scheduler.evict (the deadline/fault path) returns every
        page to the pool."""
        model, params = gpt
        engine = PagedEngine(model, params, max_slots=2, block_size=8,
                             prefill_chunk=4)
        sched = Scheduler(engine)
        sched.submit(Request(prompt=np.zeros(12, np.int32),
                             max_new_tokens=50))
        for _ in range(6):
            sched.run_step()
        assert engine.blocks_in_use >= 2
        assert sched.active_count == 1
        sched.evict(0)
        assert engine.blocks_in_use == 0
        assert sched.active_count == 0


class TestSamplingDeterminism:
    def test_tokens_independent_of_cotenants(self, gpt):
        """A sampled request's chain is a function of its own seed —
        co-tenant traffic (and the chunked prefill it causes) must not
        perturb it: the k-th produced token always consumes the k-th
        rng split (emission-gated rng advance)."""
        model, params = gpt
        rng = np.random.default_rng(7)
        prompt = rng.integers(0, model.cfg.vocab_size,
                              size=(6,)).astype(np.int32)

        def run(extra_traffic):
            engine = PagedEngine(model, params, max_slots=2,
                                 block_size=8, prefill_chunk=4)
            sched = Scheduler(engine)
            req = sched.submit(Request(
                prompt=prompt, max_new_tokens=5, temperature=0.9,
                top_k=20, seed=123))
            if extra_traffic:
                for i in range(3):
                    sched.submit(Request(
                        prompt=rng.integers(
                            0, model.cfg.vocab_size,
                            size=(4 + i,)).astype(np.int32),
                        max_new_tokens=4, temperature=1.3, seed=i))
            sched.drain()
            return list(req.tokens)

        assert run(False) == run(True)


class TestPagedServer:
    def test_streaming_parity_metrics_and_gauges(self, gpt):
        model, params = gpt
        rng = np.random.default_rng(13)
        prompt = rng.integers(0, model.cfg.vocab_size,
                              size=(9,)).astype(np.int32)
        ref = np.asarray(generate(
            model, params, jnp.asarray(prompt[None]),
            max_new_tokens=5))[0, 9:]
        rows = []
        writer = MetricsWriter(sink=lambda s, m: rows.append((s, m)))
        server = InferenceServer(
            model, params, max_slots=2, kv_cache="paged", block_size=8,
            prefill_chunk=4, metrics=writer, metrics_interval=2)
        with server:
            h1 = server.submit(prompt, max_new_tokens=5)
            h2 = server.submit(
                rng.integers(0, model.cfg.vocab_size, size=(6,)),
                max_new_tokens=3, temperature=0.8, seed=4)
            got = h1.result(timeout=300)
            assert len(h2.result(timeout=300)) == 3
            health = server.health()
        np.testing.assert_array_equal(np.asarray(got), ref)
        # the occupancy gauge and latency percentiles ride health +
        # every metrics emission
        assert health["blocks_total"] == server.engine.blocks_total
        assert health["blocks_in_use"] == 0
        assert health["preempts"] == 0
        assert rows, "metrics never emitted"
        merged = {}
        for _, m in rows:
            merged.update(m)
        assert {"tokens_per_sec", "occupancy", "queue_depth",
                "blocks_in_use", "blocks_total", "ttft_p50_s",
                "ttft_p99_s", "step_ms_p50",
                "step_ms_p99"} <= set(merged)
        assert merged["ttft_p50_s"] > 0
        summary = server.latency_summary()
        assert summary["ttft_p99_s"] >= summary["ttft_p50_s"]

    def test_invalid_kv_cache_rejected(self, gpt):
        model, params = gpt
        with pytest.raises(ValueError, match="kv_cache"):
            InferenceServer(model, params, kv_cache="sparse")


class TestTrafficModel:
    def test_serving_traffic_model_scales_with_live_tokens(self):
        """The analytic per-step KV traffic model (bench_configs):
        dense bytes pinned at max_seq_len, paged bytes ∝ live pages;
        the paged pool footprint is sized in tokens."""
        import bench_configs as bc

        cfg = dict(num_layers=4, kv_heads=2, head_dim=64,
                   max_seq_len=2048, dtype_bytes=2, slots=8,
                   block_size=16)
        small = bc._serving_traffic_model(live_tokens=128, **cfg)
        big = bc._serving_traffic_model(live_tokens=512, **cfg)
        for out in (small, big):
            assert {"dense_kv_read_bytes_per_step",
                    "paged_kv_read_bytes_per_step",
                    "dense_pool_bytes", "paged_pool_tokens"} <= set(out)
        # dense per-step reads are live-independent; paged scale ~4x
        assert small["dense_kv_read_bytes_per_step"] \
            == big["dense_kv_read_bytes_per_step"]
        ratio = (big["paged_kv_read_bytes_per_step"]
                 / small["paged_kv_read_bytes_per_step"])
        assert 3.5 <= ratio <= 4.5
        assert small["paged_kv_read_bytes_per_step"] \
            < small["dense_kv_read_bytes_per_step"]

    def test_quantized_kv_capacity_and_read_bytes(self):
        """ISSUE-8 keys: at int8 the same HBM holds >= 1.9x the tokens
        (scales INCLUDED — from 2-byte storage it lands just under
        2.0x, the scale tax), per-step quantized reads count the scale
        traffic, and kv_dtype=None leaves the dict unchanged."""
        import bench_configs as bc

        cfg = dict(num_layers=4, kv_heads=2, head_dim=64,
                   max_seq_len=2048, dtype_bytes=2, slots=8,
                   block_size=16, live_tokens=256)
        plain = bc._serving_traffic_model(**cfg)
        quant = bc._serving_traffic_model(**cfg, kv_dtype="int8")
        assert "kv_dtype" not in plain
        mult = quant["quantized_capacity_multiplier"]
        assert 1.9 <= mult < 2.0       # bf16 -> int8, scale tax real
        assert quant["paged_pool_tokens_at_equal_hbm"] \
            >= 1.9 * quant["paged_pool_tokens"]
        # quantized reads: half the page bytes plus the scale scalars
        assert quant["paged_kv_read_bytes_per_step_quantized"] \
            > quant["paged_kv_read_bytes_per_step"] // 2
        assert quant["paged_kv_read_bytes_per_step_quantized"] \
            < quant["paged_kv_read_bytes_per_step"]
        # unchanged keys stay byte-identical with the flag off
        assert {k: v for k, v in quant.items()
                if k in plain} == plain
        with pytest.raises(ValueError, match="kv_dtype"):
            bc._serving_traffic_model(**cfg, kv_dtype="int4")


class TestRefcountedAllocator:
    def test_incref_defers_free_and_counts_sharing(self):
        alloc = BlockAllocator(9, 4)
        a = alloc.alloc(2)
        assert alloc.refcount(a[0]) == 1
        assert alloc.incref(a[0]) == 2
        assert alloc.shared_blocks == 1
        assert alloc.blocks_saved == 1
        # first free decrements; the page stays allocated
        assert alloc.free([a[0]]) == []
        assert alloc.blocks_in_use == 2
        assert alloc.shared_blocks == 0
        # last reference frees for real, and is reported
        assert alloc.free([a[0]]) == [a[0]]
        assert alloc.blocks_in_use == 1
        assert alloc.free([a[1]]) == [a[1]]
        assert alloc.blocks_in_use == 0

    def test_double_free_still_raises_under_refcounts(self):
        alloc = BlockAllocator(5, 2)
        got = alloc.alloc(1)
        alloc.free(got)
        with pytest.raises(ValueError, match="double free"):
            alloc.free(got)

    def test_incref_of_free_page_raises(self):
        alloc = BlockAllocator(5, 2)
        got = alloc.alloc(1)
        alloc.free(got)
        with pytest.raises(ValueError, match="not allocated"):
            alloc.incref(got[0])


class TestPrefixTrie:
    def test_chain_digests_identify_whole_prefixes(self):
        a = np.arange(20, dtype=np.int32)
        b = a.copy()
        b[10] += 1                       # diverge inside block 1
        da, db = chain_digests(a, 8), chain_digests(b, 8)
        assert len(da) == len(db) == 2   # only FULL blocks hash
        assert da[0] == db[0]
        assert da[1] != db[1]
        # chaining: same block tokens after a divergent block differ
        c = np.concatenate([b[:8], a[8:]])
        dc = chain_digests(c, 8)
        assert dc[0] == da[0] and dc[1] == da[1]

    def test_register_match_forget(self):
        trie = PrefixTrie()
        d = chain_digests(np.arange(24, dtype=np.int32), 8)
        assert trie.register(d[0], 5)
        assert trie.register(d[1], 9)
        assert not trie.register(d[0], 7)    # first writer wins
        assert trie.match(d) == [5, 9]       # longest-prefix hit
        trie.forget(9)
        assert trie.match(d) == [5]
        assert not trie.holds_block(9) and trie.holds_block(5)
        trie.forget(9)                       # idempotent no-op
        assert len(trie) == 1


class TestPromptLookupDraft:
    def test_ngram_continuation_found(self):
        ctx = np.array([1, 2, 3, 4, 1, 2, 3], np.int32)
        np.testing.assert_array_equal(
            prompt_lookup_draft(ctx, 3), [4, 1, 2])

    def test_most_recent_match_and_fallback(self):
        # trailing [5] occurs twice: the LATER continuation wins
        ctx = np.array([5, 7, 0, 5, 9, 5], np.int32)
        np.testing.assert_array_equal(
            prompt_lookup_draft(ctx, 2, max_ngram=3), [9, 5])
        # no match anywhere -> empty (row decodes undrafted)
        assert prompt_lookup_draft(
            np.array([1, 2, 3], np.int32), 4).size == 0

    def test_k_caps_the_proposal(self):
        ctx = np.array([1, 2, 1, 2], np.int32)
        assert prompt_lookup_draft(ctx, 1).size == 1


class TestPrefixSharing:
    def test_shared_prefix_parity_gauges_and_refcounts(self, gpt):
        """Two tenants share a two-page prompt prefix: the second
        admission maps the first's pages (blocks_in_use grows by the
        PRIVATE tail only), both greedy chains match generate(), and
        the pool drains to zero."""
        model, params = gpt
        rng = np.random.default_rng(31)
        pref = rng.integers(0, model.cfg.vocab_size,
                            size=(16,)).astype(np.int32)
        pa = np.concatenate([pref, rng.integers(
            0, model.cfg.vocab_size, size=(3,)).astype(np.int32)])
        pb = np.concatenate([pref, rng.integers(
            0, model.cfg.vocab_size, size=(5,)).astype(np.int32)])
        engine = PagedEngine(model, params, max_slots=2, block_size=8,
                             prefill_chunk=4, share_prefixes=True)
        sched = Scheduler(engine)
        ra = sched.submit(Request(prompt=pa, max_new_tokens=6))
        for _ in range(6):               # A past prefill, still live
            sched.run_step()
        assert engine.trie_blocks == 2   # A's full prompt blocks
        use_before = engine.blocks_in_use
        rb = sched.submit(Request(prompt=pb, max_new_tokens=6))
        sched.run_step()
        # B's two prefix pages are MAPPED, not allocated
        assert engine.shared_blocks == 2
        assert engine.blocks_saved == 2
        assert engine.blocks_in_use <= use_before + 1
        assert engine.cow_forks == 0     # divergent tail: no fork
        sched.drain()
        for p, r in ((pa, ra), (pb, rb)):
            ref = np.asarray(generate(
                model, params, jnp.asarray(p[None]),
                max_new_tokens=6))[0, len(p):]
            np.testing.assert_array_equal(np.asarray(r.tokens), ref)
        assert engine.blocks_in_use == 0
        assert engine.shared_blocks == 0

    def test_whole_prompt_hit_cow_forks_and_stays_identical(self, gpt):
        """Page-boundary prompt fully resident in the trie: the last
        matched block is CoW-forked (re-derived private) so the
        re-fed final prompt token never writes a shared page — greedy
        output identical for both tenants."""
        model, params = gpt
        rng = np.random.default_rng(37)
        prompt = rng.integers(0, model.cfg.vocab_size,
                              size=(16,)).astype(np.int32)  # 2 pages
        engine = PagedEngine(model, params, max_slots=2, block_size=8,
                             prefill_chunk=4, share_prefixes=True)
        sched = Scheduler(engine)
        ra = sched.submit(Request(prompt=prompt, max_new_tokens=8))
        for _ in range(5):
            sched.run_step()
        rb = sched.submit(Request(prompt=prompt.copy(),
                                  max_new_tokens=8))
        sched.run_step()
        assert engine.cow_forks == 1
        assert engine.shared_blocks == 1     # block 0 shared, 1 forked
        sched.drain()
        ref = np.asarray(generate(
            model, params, jnp.asarray(prompt[None]),
            max_new_tokens=8))[0, 16:]
        np.testing.assert_array_equal(np.asarray(ra.tokens), ref)
        np.testing.assert_array_equal(np.asarray(rb.tokens), ref)
        assert engine.blocks_in_use == 0

    def test_can_admit_discounts_trie_resident_prefix(self, gpt):
        """Shared-aware token gate: a request whose prefix is resident
        admits into capacity that would block an unshared twin — the
        reclaimed pool converts into admitted occupancy."""
        model, params = gpt
        rng = np.random.default_rng(41)
        pref = rng.integers(0, model.cfg.vocab_size,
                            size=(16,)).astype(np.int32)
        engine = PagedEngine(model, params, max_slots=3, block_size=8,
                             pool_tokens=48, prefill_chunk=4,
                             admit_headroom=8, share_prefixes=True)
        sched = Scheduler(engine)
        sched.submit(Request(prompt=np.concatenate(
            [pref, rng.integers(0, model.cfg.vocab_size,
                                size=(2,)).astype(np.int32)]),
            max_new_tokens=4))
        for _ in range(6):
            sched.run_step()
        # 3 of 6 pages held; a fresh 18+8-token request needs 4 pages
        # -> blocked unshared, admitted when 2 pages are trie hits
        fresh = rng.integers(0, model.cfg.vocab_size,
                             size=(18,)).astype(np.int32)
        shared = np.concatenate([pref, fresh[:2]])
        assert not engine.can_admit(18, 8, prompt=fresh)
        assert engine.can_admit(18, 8, prompt=shared)
        assert engine.prefix_hit_blocks(shared) == 2
        assert engine.prefix_hit_blocks(fresh) == 0

    def test_preempt_requeue_reshares_and_drains(self, gpt):
        """Preemption under sharing: refcounts decrement (never
        double-free), the requeued continuation re-matches surviving
        trie pages, greedy chains stay identical, pool drains to 0."""
        model, params = gpt
        rng = np.random.default_rng(43)
        pref = rng.integers(0, model.cfg.vocab_size,
                            size=(16,)).astype(np.int32)
        p1 = np.concatenate([pref, rng.integers(
            0, model.cfg.vocab_size, size=(4,)).astype(np.int32)])
        p2 = np.concatenate([pref, rng.integers(
            0, model.cfg.vocab_size, size=(6,)).astype(np.int32)])
        engine = PagedEngine(model, params, max_slots=2, block_size=8,
                             pool_tokens=64, prefill_chunk=4,
                             admit_headroom=0, share_prefixes=True)
        sched = Scheduler(engine)
        r1 = sched.submit(Request(prompt=p1, max_new_tokens=28))
        r2 = sched.submit(Request(prompt=p2, max_new_tokens=26))
        sched.drain()
        assert sched.preempts >= 1
        for p, n, r in ((p1, 28, r1), (p2, 26, r2)):
            ref = np.asarray(generate(
                model, params, jnp.asarray(p[None]),
                max_new_tokens=n))[0, len(p):]
            np.testing.assert_array_equal(np.asarray(r.tokens), ref)
        assert engine.blocks_in_use == 0


class TestSpeculativeDecoding:
    def test_greedy_parity_across_boundaries_with_spec_on(self, gpt):
        """Draft/verify on: greedy chains must reproduce generate()
        exactly at page-boundary (8/16), chunk-boundary (4) and
        straddling prompt lengths — lookup-friendly (repetitive) and
        lookup-hostile (random) prompts alike."""
        model, params = gpt
        rng = np.random.default_rng(47)
        prompts = [np.tile(rng.integers(
            0, model.cfg.vocab_size, size=(4,)).astype(np.int32), 4)]
        for L in (4, 7, 8, 9, 16, 17):
            prompts.append(rng.integers(
                0, model.cfg.vocab_size, size=(L,)).astype(np.int32))
        engine = PagedEngine(model, params, max_slots=2, block_size=8,
                             prefill_chunk=4, spec_tokens=3)
        sched = Scheduler(engine)
        reqs = [sched.submit(Request(prompt=p, max_new_tokens=6))
                for p in prompts]
        sched.drain()
        for p, r in zip(prompts, reqs):
            ref = np.asarray(generate(
                model, params, jnp.asarray(p[None]),
                max_new_tokens=6))[0, len(p):]
            np.testing.assert_array_equal(
                np.asarray(r.tokens), ref,
                err_msg=f"prompt_len={len(p)}")
        assert engine.spec_proposed > 0      # drafting actually ran
        assert engine.blocks_in_use == 0

    def test_sampled_chains_are_acceptance_invariant(self, gpt):
        """temperature>0: the k-th produced token always consumes the
        k-th rng split, so the SAME seeded chain comes out with
        drafting off, with an ORACLE drafter (every draft accepted —
        multi-token emissions), and with a hostile drafter (every
        draft rejected — pure rollback)."""
        model, params = gpt
        rng = np.random.default_rng(53)
        prompt = np.tile(rng.integers(
            0, model.cfg.vocab_size, size=(5,)).astype(np.int32), 3)

        def run(k, drafter=None):
            engine = PagedEngine(model, params, max_slots=1,
                                 block_size=8, prefill_chunk=4,
                                 spec_tokens=k)
            if drafter is not None:
                engine._drafter = drafter
            sched = Scheduler(engine)
            req = sched.submit(Request(
                prompt=prompt, max_new_tokens=7, temperature=0.9,
                top_k=20, seed=123))
            sched.drain()
            assert engine.blocks_in_use == 0
            return (list(req.tokens), engine.spec_proposed,
                    engine.spec_accepted)

        base, _, _ = run(0)

        def oracle(context, k, ngram):
            # proposes the chain the model is about to sample
            pos = context.size - prompt.size
            return np.asarray(base[pos:pos + k], np.int32)

        def hostile(context, k, ngram):
            tok = (int(context[-1]) + 1) % model.cfg.vocab_size
            return np.full((k,), tok, np.int32)

        toks, proposed, accepted = run(3, oracle)
        assert toks == base
        assert proposed > 0 and accepted > 0   # multi-emit steps ran
        toks, proposed, accepted = run(3, hostile)
        assert toks == base
        assert proposed > 0                    # rollbacks ran

    def test_eos_inside_accepted_run_stops_exactly(self, gpt):
        """An accepted draft that samples eos mid-run must truncate
        the emission at eos — byte-for-byte the sequential stop."""
        model, params = gpt
        rng = np.random.default_rng(59)
        prompt = np.tile(rng.integers(
            0, model.cfg.vocab_size, size=(3,)).astype(np.int32), 4)
        ref = np.asarray(generate(
            model, params, jnp.asarray(prompt[None]),
            max_new_tokens=8))[0, len(prompt):]
        eos = int(ref[3])
        engine = PagedEngine(model, params, max_slots=1, block_size=8,
                             prefill_chunk=4, spec_tokens=4)
        sched = Scheduler(engine)
        req = sched.submit(Request(prompt=prompt, max_new_tokens=8,
                                   eos_id=eos))
        sched.drain()
        got = np.asarray(req.tokens)
        first = int(np.argmax(ref == eos))
        np.testing.assert_array_equal(got, ref[:first + 1])
        assert got[-1] == eos
        assert engine.blocks_in_use == 0

    def test_soak_sharing_and_spec_zero_retraces_at_budget(self, gpt):
        """The ISSUE-7 acceptance soak: mixed shared/unshared AND
        drafted/undrafted traffic with heterogeneous sampling params
        — zero retraces after warmup at the documented budget of FIVE
        executables (decode/prefill/spec/admit/release = 1 each), and
        the accept-rate gauge moves."""
        model, params = gpt
        engine = PagedEngine(model, params, max_slots=3, block_size=8,
                             prefill_chunk=4, share_prefixes=True,
                             spec_tokens=3)
        sched = Scheduler(engine)
        engine.warmup()
        budget = {"decode_step": 1, "prefill_step": 1, "spec_step": 1,
                  "admit": 1, "release": 1}
        assert engine.trace_counts == budget

        rng = np.random.default_rng(61)
        pref = rng.integers(0, model.cfg.vocab_size,
                            size=(16,)).astype(np.int32)
        before = tracecheck.trace_event_count()
        reqs = []
        for i in range(10):
            if i % 2 == 0:      # hot-prompt traffic (shared, lookupy)
                prompt = np.concatenate([pref, rng.integers(
                    0, model.cfg.vocab_size,
                    size=(1 + i // 2,)).astype(np.int32)])
            else:               # cold random traffic
                prompt = rng.integers(
                    0, model.cfg.vocab_size,
                    size=(3 + i,)).astype(np.int32)
            t, k, p = [(0.0, None, None), (0.8, 20, None),
                       (1.2, 5, 0.9)][i % 3]
            reqs.append(sched.submit(Request(
                prompt=prompt, max_new_tokens=3 + i % 4,
                temperature=t, top_k=k, top_p=p, seed=i)))
        sched.drain()
        assert tracecheck.trace_event_count() == before, (
            "sharing+spec soak retraced after warmup")
        assert engine.trace_counts == budget
        for r in reqs:
            assert len(r.tokens) == r._budget0
        assert engine.spec_proposed > 0
        assert 0.0 <= engine.spec_accept_rate <= 1.0
        assert engine.blocks_in_use == 0
        assert engine.shared_blocks == 0

    def test_server_knobs_and_gauges(self, gpt):
        """InferenceServer plumbs the knobs through and surfaces the
        new gauges in health() and metrics emissions; dense servers
        reject them loudly."""
        model, params = gpt
        with pytest.raises(ValueError, match="paged"):
            InferenceServer(model, params, spec_tokens=2)
        with pytest.raises(ValueError, match="paged"):
            InferenceServer(model, params, share_prefixes=True)
        rng = np.random.default_rng(67)
        pref = rng.integers(0, model.cfg.vocab_size,
                            size=(16,)).astype(np.int32)
        rows = []
        writer = MetricsWriter(sink=lambda s, m: rows.append((s, m)))
        server = InferenceServer(
            model, params, max_slots=2, kv_cache="paged", block_size=8,
            prefill_chunk=4, share_prefixes=True, spec_tokens=3,
            metrics=writer, metrics_interval=2)
        prompt = np.tile(pref[:4], 4).astype(np.int32)
        ref = np.asarray(generate(
            model, params, jnp.asarray(prompt[None]),
            max_new_tokens=6))[0, len(prompt):]
        with server:
            h1 = server.submit(prompt, max_new_tokens=6)
            h2 = server.submit(np.concatenate([pref, pref[:1]]),
                               max_new_tokens=4)
            got = h1.result(timeout=300)
            h2.result(timeout=300)
            health = server.health()
            assert server.prefix_hit_blocks(pref) >= 0
        np.testing.assert_array_equal(np.asarray(got), ref)
        assert {"shared_blocks", "cow_forks",
                "spec_accept_rate"} <= set(health)
        assert health["blocks_in_use"] == 0
        merged = {}
        for _, m in rows:
            merged.update(m)
        assert {"shared_blocks", "cow_forks",
                "spec_accept_rate"} <= set(merged)


class TestQuantizedKV:
    """ISSUE 8: int8/fp8 paged KV pool with per-(kv_head, page) amax
    scales riding the cache beside the block table."""

    def test_kv_dtype_validation_is_loud(self, gpt):
        model, params = gpt
        import dataclasses

        from apex_tpu.models import GPTConfig

        with pytest.raises(ValueError, match="paged"):
            dataclasses.replace(model.cfg, kv_dtype="int8")
        with pytest.raises(ValueError, match="kv_dtype"):
            dataclasses.replace(
                model.cfg, kv_cache="paged", kv_block_size=8,
                kv_pool_blocks=4, kv_dtype="int4")
        with pytest.raises(ValueError, match="paged"):
            InferenceServer(model, params, kv_dtype="int8")
        with pytest.raises(ValueError, match="kv_dtype"):
            PagedEngine(model, params, kv_dtype="int4")

    def test_equal_hbm_default_pool_capacity_at_least_1p9x(self, gpt):
        """The quantized engine's default pool converts the dense
        slab's byte budget into quantized tokens, SCALES INCLUDED:
        ≥1.9× the unquantized token capacity at int8 (~3.9× here —
        the fp32 test model stores 4-byte K/V unquantized)."""
        model, params = gpt
        base = PagedEngine(model, params, max_slots=2, block_size=8)
        quant = PagedEngine(model, params, max_slots=2, block_size=8,
                            kv_dtype="int8")
        assert quant.kv_bits == 8 and base.kv_bits == 32
        ratio = quant.pool_tokens / base.pool_tokens
        assert ratio >= 1.9, ratio
        # ... and the scale overhead was actually charged: the pool is
        # strictly smaller than a scale-free itemsize conversion
        assert quant.pool_tokens < base.pool_tokens * 4
        # an EXPLICIT pool_tokens is never silently rescaled
        pinned = PagedEngine(model, params, max_slots=2, block_size=8,
                             pool_tokens=64, kv_dtype="int8")
        assert pinned.pool_tokens == 64

    def test_page_reuse_resets_scales_deterministically(self, gpt):
        """Replay the same request on a DIRTY pool (pages + scales
        left by a released tenant): the first write of each reused
        page resets its scale, so the second chain is token-identical
        to the first — stale scales never leak into fresh tenants."""
        model, params = gpt
        rng = np.random.default_rng(71)
        engine = PagedEngine(model, params, max_slots=2, block_size=8,
                             prefill_chunk=4, kv_dtype="int8")
        sched = Scheduler(engine)
        prompts = [rng.integers(0, model.cfg.vocab_size,
                                size=(L,)).astype(np.int32)
                   for L in (7, 12)]

        def wave():
            reqs = [sched.submit(Request(prompt=p, max_new_tokens=6))
                    for p in prompts]
            sched.drain()
            assert engine.blocks_in_use == 0
            return [list(r.tokens) for r in reqs]

        first = wave()
        assert wave() == first

    def test_pad_lane_content_never_touches_page_scales(self, gpt):
        """Mixed-step pad lanes (>= the row's chunk_lens) route to the
        null page: live page scales AND codes are bitwise invariant to
        pad content.  Without the routing, a pad lane's K/V amax would
        scatter-MAX into the row's current page scale and stick
        forever (the running amax is monotone), so a tenant's page
        codes would depend on what garbage happened to ride beside it
        — breaking the scales-are-a-pure-function-of-the-row's-tokens
        invariant that shared/CoW pages rely on."""
        model, params = gpt
        import dataclasses

        from apex_tpu.models.generate import apply_decode, cache_shapes
        cfg = dataclasses.replace(
            model.cfg, kv_cache="paged", kv_block_size=8,
            kv_pool_blocks=6, kv_dtype="int8")
        paged = type(model)(cfg=cfg)
        shapes = cache_shapes(paged, 1)
        base = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                            shapes)
        mb = slot_cache.blocks_for(cfg.max_seq_len, 8)
        tables = np.zeros((1, mb), np.int32)
        tables[0, 0] = 1                 # one live page for the row

        def leaves(tree, name):
            return [np.asarray(leaf) for path, leaf
                    in jax.tree_util.tree_flatten_with_path(tree)[0]
                    if slot_cache._leaf_name(path) == name]

        def run(pad_id):
            # 2 real tokens + 2 pad lanes of width-4 mixed step
            ids = np.full((1, 4), pad_id, np.int32)
            ids[0, :2] = (3, 5)
            cache = slot_cache.set_paged_leaves(
                base, tables, np.zeros((1,), np.int32),
                np.array([2], np.int32))
            logits, cache = apply_decode(
                paged, params, cache, jnp.asarray(ids))
            return np.asarray(logits[:, :2]), cache

        ref_logits, ref_cache = run(0)
        got_logits, got_cache = run(int(model.cfg.vocab_size) - 1)
        np.testing.assert_array_equal(got_logits, ref_logits)
        for name in ("key_scales", "value_scales"):
            for ref, got in zip(leaves(ref_cache, name),
                                leaves(got_cache, name)):
                # every page but the null page (0) is bitwise pinned
                np.testing.assert_array_equal(got[..., 1:],
                                              ref[..., 1:])
        for name in ("paged_key", "paged_value"):
            for ref, got in zip(leaves(ref_cache, name),
                                leaves(got_cache, name)):
                np.testing.assert_array_equal(got[..., 1:, :, :],
                                              ref[..., 1:, :, :])

    def test_sharing_cow_and_spec_ride_quantized_pages(self, gpt):
        """Shared prefix pages, a CoW fork, and drafted steps on the
        int8 pool: a tenant reading pages another tenant wrote must
        emit the SAME chain as running alone on a fresh quantized
        engine with the same knobs (prefill chunking and drafting are
        deterministic per row, so page codes and scales are a pure
        function of the row's own token/draft history — co-tenants
        never touch them), and the pool drains with refcounts
        balanced.  The solo twin keeps spec ON: under quantization a
        REJECTED draft's amax legitimately stays in the page's
        monotone running scale (write-then-attend writes draft K/V
        before acceptance is known), so spec-on and spec-off quantized
        chains agree only within the accuracy band, not bitwise — the
        documented drift class of rescale-on-append."""
        model, params = gpt
        rng = np.random.default_rng(73)
        pref = rng.integers(0, model.cfg.vocab_size,
                            size=(16,)).astype(np.int32)
        pa = np.concatenate([pref, rng.integers(
            0, model.cfg.vocab_size, size=(3,)).astype(np.int32)])
        pb = np.concatenate([pref, rng.integers(
            0, model.cfg.vocab_size, size=(5,)).astype(np.int32)])

        solo_eng = PagedEngine(model, params, max_slots=1,
                               block_size=8, prefill_chunk=4,
                               spec_tokens=3, kv_dtype="int8")
        solo_sched = Scheduler(solo_eng)

        def solo(prompt, n):
            # ONE reused engine (compile budget): the pool drains
            # between waves and scale reset handles the dirty pages
            r = solo_sched.submit(Request(prompt=prompt,
                                          max_new_tokens=n))
            solo_sched.drain()
            assert solo_eng.blocks_in_use == 0
            return list(r.tokens)

        engine = PagedEngine(model, params, max_slots=2, block_size=8,
                             prefill_chunk=4, share_prefixes=True,
                             spec_tokens=3, kv_dtype="int8")
        sched = Scheduler(engine)
        # budget large enough that A (multi-token spec emissions) is
        # still LIVE when B arrives — a freed tenant's last-ref pages
        # leave the trie with it
        ra = sched.submit(Request(prompt=pa, max_new_tokens=14))
        for _ in range(6):               # A past prefill, still live
            sched.run_step()
        assert engine.trie_blocks == 2
        rb = sched.submit(Request(prompt=pb, max_new_tokens=6))
        sched.run_step()
        assert engine.shared_blocks == 2     # B mapped A's prefix
        # whole-prompt trie hit (16 = exactly 2 pages): CoW-forks the
        # last matched block on the quantized pool
        rc = sched.submit(Request(prompt=pref.copy(),
                                  max_new_tokens=6))
        sched.drain()
        assert engine.cow_forks >= 1
        assert list(ra.tokens) == solo(pa, 14)
        assert list(rb.tokens) == solo(pb, 6)
        assert list(rc.tokens) == solo(pref, 6)
        assert engine.spec_proposed > 0
        assert engine.blocks_in_use == 0
        assert engine.shared_blocks == 0

    def test_soak_quantized_sharing_spec_zero_retraces_at_budget(
            self, gpt):
        """The ISSUE-8 trace-discipline soak: quantization on TOP of
        sharing + drafting + heterogeneous sampling stays at exactly
        FIVE executables × 1 trace with zero retraces after warmup —
        the scale maintenance lives inside the existing step
        executables, it adds none."""
        model, params = gpt
        engine = PagedEngine(model, params, max_slots=3, block_size=8,
                             prefill_chunk=4, share_prefixes=True,
                             spec_tokens=3, kv_dtype="int8")
        sched = Scheduler(engine)
        engine.warmup()
        budget = {"decode_step": 1, "prefill_step": 1, "spec_step": 1,
                  "admit": 1, "release": 1}
        assert engine.trace_counts == budget

        rng = np.random.default_rng(79)
        pref = rng.integers(0, model.cfg.vocab_size,
                            size=(16,)).astype(np.int32)
        before = tracecheck.trace_event_count()
        reqs = []
        for i in range(8):
            if i % 2 == 0:
                prompt = np.concatenate([pref, rng.integers(
                    0, model.cfg.vocab_size,
                    size=(1 + i // 2,)).astype(np.int32)])
            else:
                prompt = rng.integers(
                    0, model.cfg.vocab_size,
                    size=(3 + i,)).astype(np.int32)
            t, k, p = [(0.0, None, None), (0.8, 20, None),
                       (1.2, 5, 0.9)][i % 3]
            reqs.append(sched.submit(Request(
                prompt=prompt, max_new_tokens=3 + i % 4,
                temperature=t, top_k=k, top_p=p, seed=i)))
        sched.drain()
        assert tracecheck.trace_event_count() == before, (
            "quantized sharing+spec soak retraced after warmup")
        assert engine.trace_counts == budget
        for r in reqs:
            assert len(r.tokens) == r._budget0
        assert engine.blocks_in_use == 0

    def test_server_surfaces_kv_dtype_in_health_and_metrics(self, gpt):
        model, params = gpt
        rows = []
        writer = MetricsWriter(sink=lambda s, m: rows.append((s, m)))
        server = InferenceServer(
            model, params, max_slots=2, kv_cache="paged", block_size=8,
            prefill_chunk=4, kv_dtype="int8", metrics=writer,
            metrics_interval=2)
        with server:
            h = server.submit(np.arange(1, 9, dtype=np.int32),
                              max_new_tokens=5)
            h.result(timeout=300)
            health = server.health()
        assert health["kv_dtype"] == "int8"
        assert health["kv_bits"] == 8
        merged = {}
        for _, m in rows:
            merged.update(m)
        assert merged.get("kv_bits") == 8.0
        # unquantized servers report the storage width of the compute
        # dtype and kv_dtype None
        server2 = InferenceServer(
            model, params, max_slots=1, kv_cache="paged", block_size=8,
            prefill_chunk=4)
        with server2:
            h2 = server2.health()
        assert h2["kv_dtype"] is None and h2["kv_bits"] == 32

    def test_kv_dtype_auto_adopts_tuned_pair(self, gpt, tmp_path,
                                             monkeypatch):
        """block_size=0 + kv_dtype='auto' adopts the joint
        (block_size, kv_dtype) winner from the autotune table; with
        nothing cached it stays unquantized at the default block."""
        model, params = gpt
        monkeypatch.setenv("APEX_TPU_AUTOTUNE_CACHE",
                           str(tmp_path / "at.json"))
        from apex_tpu.ops import autotune

        autotune.clear_cache()
        try:
            cold = PagedEngine(model, params, max_slots=1,
                               block_size=0, kv_dtype="auto")
            assert cold.kv_dtype is None and cold.block_size == 16
            autotune._store(
                autotune._key("paged_attention_pair",
                              int(model.cfg.head_dim),
                              str(jnp.dtype(model.cfg.dtype)),
                              kv_heads=int(model.cfg.kv_heads)),
                [8, "int8"])
            warm = PagedEngine(model, params, max_slots=1,
                               block_size=0, kv_dtype="auto")
            assert warm.kv_dtype == "int8" and warm.block_size == 8
            assert warm.kv_bits == 8
            # an explicit block size opts OUT of the joint pair (the
            # caller overrode the tuner): auto resolves to unquantized
            expl = PagedEngine(model, params, max_slots=1,
                               block_size=8, kv_dtype="auto")
            assert expl.kv_dtype is None
        finally:
            autotune.clear_cache()


@pytest.mark.slow
class TestQuantizedAccuracySlow:
    """The ISSUE-8 accuracy acceptance on a TRAINED proxy (a random
    init's near-tied logits flip under any perturbation and measure
    nothing): ≥95% greedy token agreement vs ``generate()`` over a
    multi-request soak horizon with kv_dtype='int8'."""

    def test_greedy_token_agreement_at_least_95pct(self):
        import jax as _jax

        from apex_tpu.models import GPTConfig, GPTModel, gpt_loss_fn

        cfg = GPTConfig.tiny(position_embedding="learned",
                             scan_layers=True)
        model = GPTModel(cfg)
        rng = np.random.default_rng(0)
        period = 24
        cyc = rng.permutation(min(cfg.vocab_size, 256))[:period] \
            .astype(np.int32)
        tparams = model.init(_jax.random.PRNGKey(0),
                             jnp.zeros((1, 4), jnp.int32))["params"]

        def cyc_batch(bs, L):
            phases = rng.integers(0, period, size=bs)
            idx = (phases[:, None] + np.arange(L + 1)) % period
            return jnp.asarray(cyc[idx])

        @_jax.jit
        def sgd_step(p, ids, lr):
            def loss_fn(p):
                logits = model.apply({"params": p}, ids[:, :-1],
                                     deterministic=True)
                return gpt_loss_fn(logits, ids[:, 1:])
            loss, grads = _jax.value_and_grad(loss_fn)(p)
            return _jax.tree.map(lambda a, g: a - lr * g, p, grads), \
                loss

        steps = 200
        for i in range(steps):
            tparams, _ = sgd_step(
                tparams, cyc_batch(8, 48),
                jnp.float32(0.5 if i < steps // 2 else 0.2))
        trained = {"params": tparams}

        budget = 20
        prompts = [np.asarray(
            cyc[(ph + np.arange(period + period // 2)) % period],
            np.int32) for ph in range(6)]
        engine = PagedEngine(model, trained, max_slots=3, block_size=8,
                             prefill_chunk=8, kv_dtype="int8")
        sched = Scheduler(engine)
        reqs = [sched.submit(Request(prompt=p, max_new_tokens=budget))
                for p in prompts]
        sched.drain()
        agree = total = 0
        for p, r in zip(prompts, reqs):
            ref = np.asarray(generate(
                model, trained, jnp.asarray(p[None]),
                max_new_tokens=budget))[0, len(p):]
            got = np.asarray(r.tokens)
            agree += int((got == ref).sum())
            total += budget
        assert engine.blocks_in_use == 0
        assert agree / total >= 0.95, (
            f"int8 KV greedy agreement {agree}/{total} "
            f"= {agree / total:.3f} < 0.95")
