"""Legacy fp16_utils API + FusedMixedPrecisionLamb + flatten parity —
mirror of the reference's ``tests/L0/run_fp16util`` (SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from apex_tpu import fp16_utils, optim, utils


def _params(rng):
    return {
        "dense": {"kernel": jnp.asarray(rng.normal(size=(8, 4)),
                                        jnp.float32),
                  "bias": jnp.zeros((4,), jnp.float32)},
        "batchnorm_0": {"scale": jnp.ones((4,), jnp.float32)},
        "step": jnp.asarray(3, jnp.int32),
    }


class TestConversions:
    def test_network_to_half_keeps_bn_fp32(self, rng):
        p = _params(rng)
        h = fp16_utils.network_to_half(p)
        assert h["dense"]["kernel"].dtype == jnp.float16
        assert h["batchnorm_0"]["scale"].dtype == jnp.float32
        assert h["step"].dtype == jnp.int32

    def test_bn_convert_float(self, rng):
        p = _params(rng)
        h = jax.tree.map(
            lambda x: x.astype(jnp.float16)
            if jnp.issubdtype(x.dtype, jnp.floating) else x, p)
        out = fp16_utils.BN_convert_float(h)
        assert out["batchnorm_0"]["scale"].dtype == jnp.float32
        assert out["dense"]["kernel"].dtype == jnp.float16

    def test_master_model_roundtrip(self, rng):
        p = fp16_utils.network_to_half(_params(rng))
        model, masters = fp16_utils.prep_param_lists(p)
        assert masters["dense"]["kernel"].dtype == jnp.float32
        back = fp16_utils.master_params_to_model_params(model, masters)
        assert back["dense"]["kernel"].dtype == jnp.float16
        np.testing.assert_allclose(
            np.asarray(back["dense"]["kernel"], np.float32),
            np.asarray(model["dense"]["kernel"], np.float32))


class TestFP16Optimizer:
    def test_training_with_dynamic_scale(self, rng):
        X = jnp.asarray(rng.normal(size=(64, 8)), jnp.float32)
        w_true = jnp.asarray(rng.normal(size=(8, 1)), jnp.float32)
        Y = X @ w_true
        params = {"w": jnp.zeros((8, 1), jnp.float16)}
        opt = fp16_utils.FP16_Optimizer(
            optax.sgd(0.1), dynamic_loss_scale=True,
            dynamic_loss_args={"init_scale": 2.0 ** 8})
        state = opt.init(params)

        @jax.jit
        def step(state, params):
            def loss_fn(p):
                pred = X.astype(jnp.float16) @ p["w"]
                loss = jnp.mean(
                    (pred.astype(jnp.float32) - Y) ** 2)
                return opt.scale_loss(state, loss), loss
            grads, loss = jax.grad(loss_fn, has_aux=True)(params)
            new_state, new_params, finite = opt.step(
                state, params, grads)
            return new_state, new_params, loss

        losses = []
        for _ in range(25):
            state, params, loss = step(state, params)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.1
        assert params["w"].dtype == jnp.float16

    def test_overflow_skips_step(self, rng):
        params = {"w": jnp.ones((4,), jnp.float16)}
        opt = fp16_utils.FP16_Optimizer(optax.sgd(0.1),
                                        dynamic_loss_scale=True)
        state = opt.init(params)
        bad = {"w": jnp.full((4,), jnp.inf, jnp.float16)}
        new_state, new_params, finite = opt.step(state, params, bad)
        assert not bool(finite)
        np.testing.assert_array_equal(
            np.asarray(new_params["w"], np.float32),
            np.asarray(params["w"], np.float32))
        assert float(new_state.loss_scale_state.loss_scale) == \
            float(state.loss_scale_state.loss_scale) / 2

    def test_state_dict_roundtrip(self, rng):
        params = {"w": jnp.ones((4,), jnp.float16)}
        opt = fp16_utils.FP16_Optimizer(optax.sgd(0.1),
                                        static_loss_scale=128.0)
        state = opt.init(params)
        d = opt.state_dict(state)
        state2 = opt.load_state_dict(d)
        assert float(state2.loss_scale_state.loss_scale) == 128.0


class TestFusedMixedPrecisionLamb:
    def test_params_track_fp32_masters(self, rng):
        params = {"w": jnp.asarray(rng.normal(size=(16, 4)),
                                   jnp.bfloat16)}
        tx = optim.fused_mixed_precision_lamb(1e-2)
        state = tx.init(params)
        assert state.master_params["w"].dtype == jnp.float32
        grads = {"w": jnp.ones((16, 4), jnp.bfloat16)}
        p = params
        for _ in range(3):
            updates, state = tx.update(grads, state, p)
            p = optax.apply_updates(p, updates)
        assert p["w"].dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(p["w"], np.float32),
            np.asarray(state.master_params["w"].astype(jnp.bfloat16),
                       np.float32))
        # masters actually moved
        assert not np.allclose(np.asarray(state.master_params["w"]),
                               np.asarray(params["w"], np.float32))

    def test_matches_plain_lamb_in_fp32(self, rng):
        w0 = jnp.asarray(rng.normal(size=(8, 8)), jnp.float32)
        grads = {"w": jnp.asarray(rng.normal(size=(8, 8)), jnp.float32)}
        tx_ref = optim.fused_lamb(1e-2)
        tx_mp = optim.fused_mixed_precision_lamb(1e-2)
        p_ref, s_ref = {"w": w0}, tx_ref.init({"w": w0})
        p_mp, s_mp = {"w": w0}, tx_mp.init({"w": w0})
        for _ in range(3):
            u, s_ref = tx_ref.update(grads, s_ref, p_ref)
            p_ref = optax.apply_updates(p_ref, u)
            u, s_mp = tx_mp.update(grads, s_mp, p_mp)
            p_mp = optax.apply_updates(p_mp, u)
        np.testing.assert_allclose(np.asarray(p_mp["w"]),
                                   np.asarray(p_ref["w"]),
                                   rtol=1e-6, atol=1e-7)


class TestFlatten:
    def test_roundtrip(self, rng):
        tree = {"a": jnp.asarray(rng.normal(size=(3, 4)), jnp.float32),
                "b": [jnp.arange(5, dtype=jnp.float32)]}
        flat, unravel = utils.flatten(tree)
        assert flat.ndim == 1 and flat.size == 17
        back = unravel(flat * 2.0)
        np.testing.assert_allclose(np.asarray(back["a"]),
                                   2 * np.asarray(tree["a"]))
        back2 = utils.unflatten(flat, tree)
        np.testing.assert_allclose(np.asarray(back2["b"][0]),
                                   np.asarray(tree["b"][0]))
