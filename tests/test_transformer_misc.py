"""broadcast_data / log_util / testing-commons coverage
(reference: ``tests/L0/run_transformer`` data & utils tests)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.core import mesh as mesh_lib
from apex_tpu.transformer import broadcast_data, log_util
from apex_tpu.transformer import testing as ttest


@pytest.fixture
def tp_mesh():
    m = mesh_lib.initialize_mesh(tensor_model_parallel_size=4,
                                 data_parallel_size=2)
    yield m
    mesh_lib.destroy_mesh()


class TestBroadcastData:
    def test_places_replicated_over_model_axes(self, tp_mesh):
        batch = {"text": np.arange(16, dtype=np.int32).reshape(2, 8),
                 "types": np.zeros((2, 8), np.int32)}
        out = broadcast_data(["text", "types"], batch, jnp.int32)
        spec = out["text"].sharding.spec
        assert "tensor" not in jax.tree.leaves(spec)
        np.testing.assert_array_equal(np.asarray(out["text"]),
                                      batch["text"])

    def test_validates_keys_and_dtype(self, tp_mesh):
        with pytest.raises(KeyError):
            broadcast_data(["missing"], {}, jnp.int32)
        with pytest.raises(TypeError):
            broadcast_data(["x"], {"x": np.zeros(2, np.float32)},
                           jnp.int32)


class TestLogUtil:
    def test_logger_namespacing(self):
        lg = log_util.get_transformer_logger("schedules")
        assert lg.name == "apex_tpu.transformer.schedules"
        log_util.set_logging_level("WARNING")


class TestCommons:
    def test_standalone_models_forward(self):
        model, params = ttest.standalone_gpt()
        ids, labels = ttest.random_token_batch(
            jax.random.PRNGKey(1), 2, 16, model.cfg.vocab_size)
        logits = model.apply({"params": params}, ids)
        assert logits.shape == (2, 16, model.cfg.vocab_size)

        bmodel, bparams = ttest.standalone_bert()
        out = bmodel.apply({"params": bparams},
                           jnp.zeros((2, 8), jnp.int32))
        assert jax.tree.leaves(out)[0].shape[0] == 2
