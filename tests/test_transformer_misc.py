"""broadcast_data / log_util / testing-commons coverage
(reference: ``tests/L0/run_transformer`` data & utils tests)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.core import mesh as mesh_lib
from apex_tpu.transformer import broadcast_data, log_util
from apex_tpu.transformer import testing as ttest


@pytest.fixture
def tp_mesh():
    m = mesh_lib.initialize_mesh(tensor_model_parallel_size=4,
                                 data_parallel_size=2)
    yield m
    mesh_lib.destroy_mesh()


class TestBroadcastData:
    def test_places_replicated_over_model_axes(self, tp_mesh):
        batch = {"text": np.arange(16, dtype=np.int32).reshape(2, 8),
                 "types": np.zeros((2, 8), np.int32)}
        out = broadcast_data(["text", "types"], batch, jnp.int32)
        spec = out["text"].sharding.spec
        assert "tensor" not in jax.tree.leaves(spec)
        np.testing.assert_array_equal(np.asarray(out["text"]),
                                      batch["text"])

    def test_validates_keys_and_dtype(self, tp_mesh):
        with pytest.raises(KeyError):
            broadcast_data(["missing"], {}, jnp.int32)
        with pytest.raises(TypeError):
            broadcast_data(["x"], {"x": np.zeros(2, np.float32)},
                           jnp.int32)


class TestLogUtil:
    def test_logger_namespacing(self):
        lg = log_util.get_transformer_logger("schedules")
        assert lg.name == "apex_tpu.transformer.schedules"
        log_util.set_logging_level("WARNING")


class TestCommons:
    def test_standalone_models_forward(self):
        model, params = ttest.standalone_gpt()
        ids, labels = ttest.random_token_batch(
            jax.random.PRNGKey(1), 2, 16, model.cfg.vocab_size)
        logits = model.apply({"params": params}, ids)
        assert logits.shape == (2, 16, model.cfg.vocab_size)

        bmodel, bparams = ttest.standalone_bert()
        out = bmodel.apply({"params": bparams},
                           jnp.zeros((2, 8), jnp.int32))
        assert jax.tree.leaves(out)[0].shape[0] == 2


class TestFunctionalNamespace:
    def test_fused_scale_mask_softmax_wrapper(self, rng):
        from apex_tpu.transformer import functional as F
        x = jnp.asarray(rng.normal(size=(2, 2, 8, 8)), jnp.float32)
        sm = F.FusedScaleMaskSoftmax(F.AttnMaskType.causal, scale=0.5)
        out = sm(x)
        # causal: last key column masked for first query row
        assert float(out[0, 0, 0, -1]) == 0.0
        np.testing.assert_allclose(np.asarray(out.sum(-1)), 1.0,
                                   rtol=1e-5)

    def test_padding_mask_variant(self, rng):
        from apex_tpu.transformer import functional as F
        x = jnp.asarray(rng.normal(size=(1, 1, 4, 4)), jnp.float32)
        mask = jnp.zeros((1, 1, 4, 4), bool).at[..., -1].set(True)
        out = F.FusedScaleMaskSoftmax(F.AttnMaskType.padding)(x, mask)
        assert bool(jnp.all(out[..., -1] == 0.0))

    def test_rope_functional(self, rng):
        from apex_tpu.transformer import functional as F
        from apex_tpu.ops.rope import rope_cos_sin, rope_reference
        t = jnp.asarray(rng.normal(size=(2, 8, 2, 16)), jnp.float32)
        out = F.fused_apply_rotary_pos_emb(t)
        cos, sin = rope_cos_sin(8, 16)
        want = rope_reference(t, cos, sin)
        np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)
        cached = F.fused_apply_rotary_pos_emb_cached(t, cos, sin)
        np.testing.assert_allclose(np.asarray(cached), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)

    def test_xentropy_class_alias(self, rng):
        from apex_tpu.contrib import SoftmaxCrossEntropyLoss
        logits = jnp.asarray(rng.normal(size=(4, 16)), jnp.float32)
        labels = jnp.asarray([0, 3, 15, 7])
        ce = SoftmaxCrossEntropyLoss(smoothing=0.1)
        out = ce(logits, labels)
        assert out.shape == (4,)
        assert bool(jnp.all(jnp.isfinite(out)))
