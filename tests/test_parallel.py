"""SyncBatchNorm and DDP tests on the virtual CPU mesh — the hermetic
version of the reference's ``tests/distributed/synced_batchnorm`` and
``tests/distributed/DDP`` two-GPU suites (SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import flax.linen as nn
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu.core import mesh as mesh_lib
from apex_tpu import parallel as apx_parallel
from apex_tpu.parallel import (
    SyncBatchNorm, sync_batch_norm_stats, convert_syncbn_model,
    DistributedDataParallel, zero_param_specs,
)


def shard_map(fn, mesh, in_specs, out_specs, **kw):
    kw.setdefault("check_vma", False)
    return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, **kw)


@pytest.fixture
def dp_mesh():
    m = mesh_lib.initialize_mesh(data_parallel_size=8)
    yield m
    mesh_lib.destroy_mesh()


class TestSyncBatchNorm:
    def test_stats_match_global_batch(self, dp_mesh, rng):
        # stats over 8 shards == stats over the concatenated batch
        x = jnp.asarray(rng.normal(size=(16, 4, 4, 8)), jnp.float32)

        f = shard_map(
            lambda xs: sync_batch_norm_stats(
                xs, ("data",), reduce_dims=(0, 1, 2)),
            dp_mesh, (P("data"),), (P(), P()))
        mean, var = f(x)
        want_mean = np.mean(np.asarray(x), axis=(0, 1, 2))
        want_var = np.var(np.asarray(x), axis=(0, 1, 2))
        np.testing.assert_allclose(np.asarray(mean), want_mean, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(var), want_var,
                                   rtol=1e-4, atol=1e-6)

    @pytest.mark.l0
    def test_module_matches_single_device_bn(self, dp_mesh, rng):
        # the reference's canonical test: 2-process SyncBN == 1-process BN
        x = jnp.asarray(rng.normal(size=(16, 8)), jnp.float32)
        sbn = SyncBatchNorm(use_running_average=False)
        variables = sbn.init(jax.random.PRNGKey(0), x)

        def fwd(xs):
            y, _ = sbn.apply(variables, xs, mutable=["batch_stats"])
            return y

        y_sharded = shard_map(fwd, dp_mesh, (P("data"),),
                              P("data"))(x)
        bn = nn.BatchNorm(use_running_average=False, momentum=0.9)
        bn_vars = bn.init(jax.random.PRNGKey(0), x)
        y_single, _ = bn.apply(bn_vars, x, mutable=["batch_stats"])
        np.testing.assert_allclose(np.asarray(y_sharded),
                                   np.asarray(y_single),
                                   rtol=1e-4, atol=1e-5)

    def test_gradients_cross_device_terms(self, dp_mesh, rng):
        # grad wrt x must include the cross-shard stat terms: compare
        # sharded-grad vs single-device autodiff of plain BN
        x = jnp.asarray(rng.normal(size=(16, 8)), jnp.float32)
        sbn = SyncBatchNorm(use_running_average=False)
        variables = sbn.init(jax.random.PRNGKey(0), x)

        def g_sharded(xs):
            def loss(xs):
                y, _ = sbn.apply(variables, xs, mutable=["batch_stats"])
                return jnp.sum(y ** 3)  # nonlinear so stat grads matter
            return jax.grad(loss)(xs)

        gs = shard_map(g_sharded, dp_mesh, (P("data"),), P("data"))(x)

        bn = nn.BatchNorm(use_running_average=False)
        bn_vars = bn.init(jax.random.PRNGKey(0), x)

        def loss_single(x):
            y, _ = bn.apply(bn_vars, x, mutable=["batch_stats"])
            return jnp.sum(y ** 3)

        # NOTE: per-shard grad omits cross-shard x-terms of OTHER shards'
        # losses; but loss is a sum over shards and grads add — with the
        # shared global stats the sharded grad equals the global grad.
        gd = jax.grad(loss_single)(x)
        np.testing.assert_allclose(np.asarray(gs), np.asarray(gd),
                                   rtol=1e-3, atol=1e-4)

    def test_running_stats_update(self, dp_mesh, rng):
        x = jnp.asarray(rng.normal(size=(16, 8)) + 3.0, jnp.float32)
        sbn = SyncBatchNorm(use_running_average=False, momentum=0.5)
        variables = sbn.init(jax.random.PRNGKey(0), x)

        def fwd(xs):
            _, upd = sbn.apply(variables, xs, mutable=["batch_stats"])
            return upd["batch_stats"]["mean"], upd["batch_stats"]["var"]

        mean, var = shard_map(fwd, dp_mesh, (P("data"),), (P(), P()))(x)
        want = 0.5 * 0.0 + 0.5 * np.mean(np.asarray(x), axis=0)
        np.testing.assert_allclose(np.asarray(mean), want, rtol=1e-4)
        # running_var stores the unbiased (ddof=1) estimate — torch
        # SyncBatchNorm parity
        want_var = 0.5 * 1.0 + 0.5 * np.var(np.asarray(x), axis=0, ddof=1)
        np.testing.assert_allclose(np.asarray(var), want_var,
                                   rtol=1e-4, atol=1e-5)

    def test_eval_mode_uses_running(self, rng):
        x = jnp.asarray(rng.normal(size=(4, 8)), jnp.float32)
        sbn = SyncBatchNorm(use_running_average=True)
        variables = sbn.init(jax.random.PRNGKey(0), x)
        y = sbn.apply(variables, x)
        # running stats are (0, 1) at init → y == scale*x + bias == x
        np.testing.assert_allclose(np.asarray(y), np.asarray(x),
                                   rtol=1e-5)

    def test_convert_syncbn_model(self):
        class Net(nn.Module):
            bn: nn.Module = None

            @nn.compact
            def __call__(self, x):
                return self.bn(x)

        net = Net(bn=nn.BatchNorm(use_running_average=False,
                                  momentum=0.8))
        converted = convert_syncbn_model(net)
        assert isinstance(converted.bn, SyncBatchNorm)
        assert converted.bn.momentum == 0.8

    def test_local_fallback_no_mesh(self, rng):
        # outside shard_map: behaves as plain BN (reference python impl
        # fallback path)
        x = jnp.asarray(rng.normal(size=(8, 4)), jnp.float32)
        sbn = SyncBatchNorm(use_running_average=False)
        variables = sbn.init(jax.random.PRNGKey(0), x)
        y, _ = sbn.apply(variables, x, mutable=["batch_stats"])
        np.testing.assert_allclose(np.mean(np.asarray(y), axis=0), 0.0,
                                   atol=1e-5)


class TestSyncBatchNormFused:
    """ISSUE-3 acceptance: the fused-stats path (the kernels' partial
    Σx/Σx² psum'd over the data axis) must keep cross-device agreement
    on the 8-device CPU mesh — same contracts as TestSyncBatchNorm,
    with ``fused=True``."""

    @pytest.mark.l0
    def test_fused_module_matches_single_device_bn(self, dp_mesh, rng):
        x = jnp.asarray(rng.normal(size=(16, 8)), jnp.float32)
        sbn = SyncBatchNorm(use_running_average=False, fused=True)
        variables = sbn.init(jax.random.PRNGKey(0), x)

        def fwd(xs):
            y, _ = sbn.apply(variables, xs, mutable=["batch_stats"])
            return y

        y_sharded = shard_map(fwd, dp_mesh, (P("data"),),
                              P("data"))(x)
        bn = nn.BatchNorm(use_running_average=False, momentum=0.9)
        bn_vars = bn.init(jax.random.PRNGKey(0), x)
        y_single, _ = bn.apply(bn_vars, x, mutable=["batch_stats"])
        np.testing.assert_allclose(np.asarray(y_sharded),
                                   np.asarray(y_single),
                                   rtol=1e-4, atol=1e-5)

    @pytest.mark.slow
    def test_fused_matches_unfused_across_mesh(self, dp_mesh, rng):
        """fwd, running stats AND input grads agree between fused and
        unfused across the 8-shard mesh — including the fused relu +
        residual epilogue.  [slow: the grad-of-shard_map compile ≈
        17 s on CPU; the fast tier keeps the single-device-BN
        agreement test below]"""
        x = jnp.asarray(rng.normal(size=(16, 8)), jnp.float32)
        res = jnp.asarray(rng.normal(size=(16, 8)), jnp.float32)
        a = SyncBatchNorm(use_running_average=False, act="relu")
        b = SyncBatchNorm(use_running_average=False, act="relu",
                          fused=True)
        variables = a.init(jax.random.PRNGKey(0), x)

        def run(mod):
            def g(xs, rs):
                def loss(xs):
                    y, upd = mod.apply(variables, xs, residual=rs,
                                       mutable=["batch_stats"])
                    return jnp.sum(y ** 3), (y, upd)
                grads, (y, upd) = jax.grad(loss, has_aux=True)(xs)
                return y, grads, upd["batch_stats"]["mean"], \
                    upd["batch_stats"]["var"]
            return shard_map(
                g, dp_mesh, (P("data"), P("data")),
                (P("data"), P("data"), P(), P()))(x, res)

        ya, ga, ma, va = run(a)
        yb, gb, mb, vb = run(b)
        np.testing.assert_allclose(np.asarray(ya), np.asarray(yb),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(ga), np.asarray(gb),
                                   rtol=1e-3, atol=1e-4)
        np.testing.assert_allclose(np.asarray(ma), np.asarray(mb),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(va), np.asarray(vb),
                                   rtol=1e-4, atol=1e-6)

    @pytest.mark.slow
    def test_fused_resnet_syncbn_step_on_mesh(self, dp_mesh, rng):
        """The resnet50_syncbn bench topology at test size: a fused_bn
        ResNet under shard_map over the data axis produces the same
        logits as the unfused module path.  [slow: two sharded resnet
        compiles ≈ 29 s on CPU]"""
        from apex_tpu.models.resnet import ResNet, ResNetConfig

        x = jnp.asarray(rng.normal(size=(16, 16, 16, 3)), jnp.float32)
        cfg = ResNetConfig(stage_sizes=(1,), num_classes=4, width=8,
                           bn_axis_names=("data",))
        m = ResNet(cfg)
        import dataclasses
        mf = ResNet(dataclasses.replace(cfg, fused_bn=True))
        variables = m.init(jax.random.PRNGKey(0), x[:2], train=True)

        def fwd(model):
            def f(xs):
                out, _ = model.apply(variables, xs, train=True,
                                     mutable=["batch_stats"])
                return out
            return shard_map(f, dp_mesh, (P("data"),), P("data"))(x)

        np.testing.assert_allclose(
            np.asarray(fwd(mf)), np.asarray(fwd(m)),
            rtol=1e-4, atol=1e-4)


class TestDDP:
    @pytest.mark.l0
    def test_sharded_training_matches_single_device(self, dp_mesh, rng):
        # end-to-end: DP training step over 8 shards == single-device
        # step on the full batch (apex DDP's correctness contract)
        import optax
        from apex_tpu import optim as ao

        x = jnp.asarray(rng.normal(size=(32, 8)), jnp.float32)
        y = jnp.asarray(rng.normal(size=(32, 2)), jnp.float32)
        params = {"w": jnp.asarray(rng.normal(size=(8, 2)), jnp.float32),
                  "b": jnp.zeros((2,), jnp.float32)}
        tx = ao.fused_sgd(0.1, momentum=0.9)
        opt_state = tx.init(params)

        def local_loss(p, xs, ys):
            pred = xs @ p["w"] + p["b"]
            return jnp.mean((pred - ys) ** 2)

        def dp_step(p, s, xs, ys):
            g = jax.grad(local_loss)(p, xs, ys)
            g = apx_parallel.all_reduce_mean_grads(g, "data")
            updates, s2 = tx.update(g, s, p)
            import optax as _o
            return _o.apply_updates(p, updates), s2

        f = shard_map(dp_step, dp_mesh,
                      (P(), P(), P("data"), P("data")), (P(), P()))
        p_dp, _ = f(params, opt_state, x, y)

        g_full = jax.grad(local_loss)(params, x, y)
        updates, _ = tx.update(g_full, opt_state, params)
        import optax as _o
        p_single = _o.apply_updates(params, updates)
        for k in params:
            np.testing.assert_allclose(np.asarray(p_dp[k]),
                                       np.asarray(p_single[k]),
                                       rtol=1e-5, atol=1e-6)

    def test_ddp_wrapper_placement(self, dp_mesh, rng):
        ddp = DistributedDataParallel(dp_mesh)
        params = {"w": jnp.ones((4, 4))}
        p = ddp.replicate(params)
        batch = ddp.shard({"x": jnp.ones((16, 4))})
        assert p["w"].sharding.is_fully_replicated
        assert not batch["x"].sharding.is_fully_replicated

    def test_zero_param_specs(self, dp_mesh):
        params = {"w": jnp.ones((16, 4)), "scalar": jnp.ones(())}
        specs = zero_param_specs(params, axis="data", mesh=dp_mesh)
        assert specs["w"] == P("data", None)
        assert specs["scalar"] == P()


class TestCompressedAllreduce:
    def test_half_allreduce_close_to_fp32(self, dp_mesh, rng):
        g = jnp.asarray(rng.normal(size=(64, 8)), jnp.float32)

        def run(dtype):
            f = shard_map(
                lambda gs: apx_parallel.all_reduce_mean_grads(
                    {"g": gs}, allreduce_dtype=dtype)["g"],
                dp_mesh, (P("data"),), P("data"))
            return np.asarray(f(g))

        exact = run(None)
        half = run(jnp.bfloat16)
        assert half.dtype == np.float32
        np.testing.assert_allclose(half, exact, rtol=2e-2, atol=2e-2)

    def test_int8_allreduce_quantization_error_bounded(self, dp_mesh, rng):
        g = jnp.asarray(rng.normal(size=(64, 8)), jnp.float32)

        f = shard_map(
            lambda gs: apx_parallel.all_reduce_mean_grads(
                {"g": gs}, allreduce_dtype="int8")["g"],
            dp_mesh, (P("data"),), P("data"))
        exact = shard_map(
            lambda gs: apx_parallel.all_reduce_mean_grads(
                {"g": gs})["g"],
            dp_mesh, (P("data"),), P("data"))
        got, want = np.asarray(f(g)), np.asarray(exact(g))
        amax = np.abs(np.asarray(g)).max()
        # per-element error ≤ quantization step (amax/127)
        assert np.abs(got - want).max() <= amax / 127 + 1e-6

    def test_int8_zero_grads(self, dp_mesh):
        g = jnp.zeros((16, 4), jnp.float32)
        f = shard_map(
            lambda gs: apx_parallel.all_reduce_mean_grads(
                {"g": gs}, allreduce_dtype="int8")["g"],
            dp_mesh, (P("data"),), P("data"))
        np.testing.assert_array_equal(np.asarray(f(g)), 0.0)

    def test_int8_dtype_object_and_validation(self, dp_mesh, rng):
        g = jnp.asarray(rng.normal(size=(16, 4)), jnp.float32)
        # jnp.int8 the dtype object routes to the quantized path
        f = shard_map(
            lambda gs: apx_parallel.all_reduce_mean_grads(
                {"g": gs}, allreduce_dtype=jnp.int8)["g"],
            dp_mesh, (P("data"),), P("data"))
        out = np.asarray(f(g))
        assert np.abs(out).max() > 0
        with pytest.raises(ValueError, match="allreduce_dtype"):
            apx_parallel.all_reduce_mean_grads(
                {"g": g}, allreduce_dtype="int4")
        with pytest.raises(ValueError, match="allreduce_dtype"):
            apx_parallel.all_reduce_mean_grads(
                {"g": g}, allreduce_dtype=jnp.int32)

    def test_int8_subnormal_amax_no_nan(self, dp_mesh):
        # amax in (0, ~3.7e-37): an unguarded 127/amax overflows to
        # +inf and 0*inf = NaN would poison zero grad elements.
        # 1e-37 > finfo.tiny, so a guard at finfo.tiny misses it
        g = jnp.full((16, 4), 1e-37, jnp.float32).at[0, 0].set(0.0)
        f = shard_map(
            lambda gs: apx_parallel.all_reduce_mean_grads(
                {"g": gs}, allreduce_dtype="int8")["g"],
            dp_mesh, (P("data"),), P("data"))
        out = np.asarray(f(g))
        assert np.isfinite(out).all(), \
            "subnormal amax must not produce NaN gradients"

    def test_int8_wire_dtype_is_int8(self, dp_mesh, rng):
        # the collectives that move O(n) payload must run on int8
        # operands — that IS the compression claim
        g = jnp.asarray(rng.normal(size=(64, 8)), jnp.float32)
        f = jax.jit(shard_map(
            lambda gs: apx_parallel.all_reduce_mean_grads(
                {"g": gs}, allreduce_dtype="int8")["g"],
            dp_mesh, (P("data"),), P("data")))
        hlo = f.lower(g).as_text()  # StableHLO text
        for op in ("stablehlo.all_to_all", "stablehlo.all_gather"):
            ops = [l for l in hlo.splitlines() if op in l]
            assert ops, f"expected a {op} in the lowered module"
            assert all("xi8>" in l for l in ops), \
                f"{op} payload must be int8 on the wire:\n" + "\n".join(ops)

    def test_int8_propagates_nonfinite(self, dp_mesh):
        g = jnp.full((16, 4), jnp.inf, jnp.float32)
        f = shard_map(
            lambda gs: apx_parallel.all_reduce_mean_grads(
                {"g": gs}, allreduce_dtype="int8")["g"],
            dp_mesh, (P("data"),), P("data"))
        out = np.asarray(f(g))
        assert not np.isfinite(out).any(), \
            "overflow must survive the quantized all-reduce"

    def test_sum_mode_keeps_compression(self, dp_mesh, rng):
        g = jnp.asarray(rng.normal(size=(16, 4)), jnp.float32)
        mean = shard_map(
            lambda gs: apx_parallel.all_reduce_mean_grads(
                {"g": gs}, allreduce_dtype="int8")["g"],
            dp_mesh, (P("data"),), P("data"))(g)
        total = shard_map(
            lambda gs: apx_parallel.all_reduce_mean_grads(
                {"g": gs}, allreduce_dtype="int8", average=False)["g"],
            dp_mesh, (P("data"),), P("data"))(g)
        np.testing.assert_allclose(np.asarray(total),
                                   np.asarray(mean) * 8, rtol=1e-5)


class TestZeroSharding:
    """distributed_fused_adam/zero_shardings (reference:
    apex/contrib/optimizers/distributed_fused_adam — ZeRO as placement,
    SURVEY.md §2.7): sharded-state training must match replicated
    training, lower to real reduce-scatter/all-gather collectives, and
    actually cut per-device state memory."""

    def test_zero_matches_replicated_and_shards_memory(self, rng):
        import optax

        from apex_tpu import amp
        from apex_tpu.parallel.distributed_optim import (
            distributed_fused_adam, zero_shardings)

        mesh = mesh_lib.initialize_mesh(fsdp_size=4,
                                        data_parallel_size=2)
        try:
            hid = 64
            w = jnp.asarray(rng.normal(size=(hid, hid)) * 0.1,
                            jnp.float32)
            b = jnp.zeros((hid,), jnp.float32)
            params = {"w": w, "b": b}
            x = jnp.asarray(rng.normal(size=(8, hid)), jnp.float32)
            y = jnp.asarray(rng.normal(size=(8, hid)), jnp.float32)

            def apply_fn(p, x):
                return jnp.tanh(x @ p["w"] + p["b"])

            def make_state():
                return amp.initialize(apply_fn, params,
                                      distributed_fused_adam(1e-2),
                                      opt_level="O2",
                                      half_dtype=jnp.bfloat16)

            def train_step(state, x, y):
                def loss_fn(p):
                    out = state.apply_fn(
                        state.policy.cast_to_compute(p), x)
                    loss = jnp.mean((out.astype(jnp.float32) - y) ** 2)
                    return state.scale_loss(loss), loss

                grads, loss = jax.grad(loss_fn, has_aux=True)(
                    state.params)
                new_state, _ = state.apply_gradients(grads=grads)
                return new_state, loss

            # replicated run (no sharding constraints)
            state_r = make_state()
            step_r = jax.jit(train_step)
            losses_r = []
            for _ in range(3):
                state_r, loss = step_r(state_r, x, y)
                losses_r.append(float(loss))

            # ZeRO run: params + optimizer state sharded over fsdp
            state_z = make_state()
            shardings = zero_shardings(state_z, mesh=mesh)
            state_z = jax.device_put(state_z, shardings)
            step_z = jax.jit(train_step,
                             in_shardings=(shardings,
                                           NamedSharding(mesh, P("data")),
                                           NamedSharding(mesh, P("data"))),
                             out_shardings=(shardings, None),
                             donate_argnums=(0,))
            xs = jax.device_put(x, NamedSharding(mesh, P("data")))
            ys = jax.device_put(y, NamedSharding(mesh, P("data")))
            lowered = step_z.lower(state_z, xs, ys)
            compiled = lowered.compile()
            losses_z = []
            for _ in range(3):
                state_z, loss = compiled(state_z, xs, ys)
                losses_z.append(float(loss))

            np.testing.assert_allclose(losses_z, losses_r,
                                       rtol=1e-5, atol=1e-6)
            # the GSPMD lowering must contain the ZeRO choreography
            hlo = compiled.as_text()
            assert ("reduce-scatter" in hlo or "all-gather" in hlo
                    or "all-reduce" in hlo), "no collectives in HLO"
            # per-device state memory: the (hid, hid) fp32 leaves of
            # params+masters+moments shard 4x over fsdp
            mat_bytes = hid * hid * 4
            arg_bytes = compiled.memory_analysis().argument_size_in_bytes
            # replicated state would hold >= 4 full fp32 matrices
            # (masters, m, v, bf16 copy) per device; sharded must be
            # well under that
            assert arg_bytes < 3 * mat_bytes, (arg_bytes, mat_bytes)
        finally:
            mesh_lib.destroy_mesh()


class TestLaunch:
    """init_distributed (reference: apex.parallel.multiproc launcher ->
    jax.distributed; MASTER_ADDR/RANK/WORLD_SIZE conventions)."""

    def test_single_host_noop_and_env_bootstrap(self):
        import subprocess
        import sys

        code = (
            "import os\n"
            "from apex_tpu.parallel import init_distributed, "
            "is_distributed\n"
            "assert init_distributed() is False\n"
            "assert not is_distributed()\n"
            "os.environ['MASTER_ADDR'] = '127.0.0.1'\n"
            "os.environ['MASTER_PORT'] = '29777'\n"
            "os.environ['WORLD_SIZE'] = '1'\n"
            "os.environ['RANK'] = '0'\n"
            "assert init_distributed() is True\n"
            "assert is_distributed()\n"
            "assert init_distributed() is True  # idempotent\n"
            "import jax\n"
            "assert jax.process_count() == 1\n"
            "print('LAUNCH_OK')\n")
        env = dict(__import__("os").environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
        r = subprocess.run([sys.executable, "-c", code], env=env,
                           capture_output=True, text=True, timeout=300)
        assert r.returncode == 0, r.stderr[-1500:]
        assert "LAUNCH_OK" in r.stdout

    def test_partial_env_raises_descriptive(self, monkeypatch):
        # round-2 advisor: MASTER_ADDR without WORLD_SIZE/RANK must
        # surface as a descriptive error naming the missing vars, not a
        # JAX-internal failure from initialize(num_processes=None);
        # match the dynamic per-case prefix, not the static tail
        from apex_tpu.parallel import init_distributed

        monkeypatch.setenv("MASTER_ADDR", "127.0.0.1")
        monkeypatch.delenv("WORLD_SIZE", raising=False)
        monkeypatch.delenv("RANK", raising=False)
        with pytest.raises(ValueError,
                           match="WORLD_SIZE and RANK unresolved"):
            init_distributed()
        monkeypatch.setenv("WORLD_SIZE", "2")
        with pytest.raises(ValueError, match=r"with RANK unresolved"):
            init_distributed()
